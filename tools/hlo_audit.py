"""Per-fusion HBM-traffic audit from a jax.profiler xplane.pb.

The roofline instrument VERDICT r3 asked for: every HLO instruction's
device self-time, measured memory bandwidth, FLOP rate, and bound_by
verdict, bucketed by category — so "X is bandwidth-bound" is a table, not
an assertion. Bytes moved per fusion = measured BW x self-time.
Usage: python tools/hlo_audit.py <xplane.pb> [steps] [top_n]
"""
import json
import sys


def main(pb, steps=10, top_n=30):
    from xprof.convert import raw_to_tool_data as rtd

    data, _ = rtd.xspace_to_tool_data([pb], "hlo_stats", {})
    obj = json.loads(data) if isinstance(data, (str, bytes)) else data
    table = obj[0] if isinstance(obj, list) else obj
    cols = [c["id"] for c in table["cols"]]
    rows = [[c["v"] for c in r["c"]] for r in table["rows"]]
    ix = {c: cols.index(c) for c in (
        "category", "hlo_op_name", "tf_op_name", "occurrences",
        "total_self_time", "measured_memory_bw", "normalized_flop_rate",
        "bound_by", "hlo_op_expression")}
    total_us = sum(r[ix["total_self_time"]] for r in rows)
    print(f"device busy {total_us/1e3:.1f} ms total / {steps} steps = "
          f"{total_us/1e3/steps:.2f} ms/step")
    # by category
    cats = {}
    for r in rows:
        c = r[ix["category"]]
        t = r[ix["total_self_time"]]
        gb = r[ix["measured_memory_bw"]] * t / 1e9  # GB/s * us -> KB... see below
        cats.setdefault(c, [0.0, 0.0])
        cats[c][0] += t
        cats[c][1] += gb
    print("\n-- by category (per step) --")
    print(f"{'ms':>8} {'%':>6} {'GB moved':>9}  category")
    for c, (t, gb) in sorted(cats.items(), key=lambda kv: -kv[1][0]):
        # measured_memory_bw is GB/s; t in us -> bytes = bw*1e9 * t*1e-6
        print(f"{t/1e3/steps:8.3f} {t/total_us*100:6.1f} "
              f"{gb*1e3/steps:9.3f}  {c}")
    print(f"\n-- top {top_n} instructions (per step) --")
    print(f"{'ms':>7} {'BW GB/s':>8} {'TF/s':>7} {'bound':>10}  op")
    for r in sorted(rows, key=lambda r: -r[ix["total_self_time"]])[:top_n]:
        t = r[ix["total_self_time"]] / steps / 1e3
        bw = r[ix["measured_memory_bw"]]
        fl = r[ix["normalized_flop_rate"]] / 1e3
        name = str(r[ix["tf_op_name"]])[:46]
        expr = str(r[ix["hlo_op_expression"]])
        shape = expr.split(" = ")[1].split(" ")[0][:28] if " = " in expr else ""
        print(f"{t:7.3f} {bw:8.1f} {fl:7.1f} {str(r[ix['bound_by']]):>10}  "
              f"{r[ix['category']][:18]:18s} {shape:28s} {name}")


if __name__ == "__main__":
    main(sys.argv[1],
         int(sys.argv[2]) if len(sys.argv) > 2 else 10,
         int(sys.argv[3]) if len(sys.argv) > 3 else 30)

"""Seq2seq per-fusion MFU ceiling audit (the r5 open item: 33% MFU bar,
no audited ceiling).

The transformer/ResNet bars are defended by per-fusion audits (BASELINE.md
"Roofline-adjusted..."); seq2seq's 33% bar was only ever a measured
number. This probe composes the r4/r5 trace ledger (docs/perf.md
"Sequence workloads" + "Seq2seq round 5" — hlo_stats-attributed device
time per term, each term's bound mechanism named) into a defended
ceiling the same way: every term is priced at its MECHANISM floor —
measured per-shape matmul rates for the MXU terms, the measured VMEM
write bound for the scan-body fusions, HBM stream rates for the
optimizer/stacking traffic — and the ceiling is total model FLOPs over
the floor-sum step time.

Terms (per bench step: B=128, T=64, E=H=512, V=30k, fwd+bwd under AMP,
r5 measured 15.53 ms = 33.6% MFU):

* head matmuls (CE head + its dW/dx): measured 160-190 TF/s, already
  within ~5% of the audited per-shape rates — floor ~4.1 ms.
* scan bodies (LSTM cell + attention fusions fwd/bwd): VMEM-write-bound
  at the measured ~2.4 TB/s, 7-config ledger of negatives — floor
  ~3.2 ms.
* gate projections + CE statistics (the hoisted [N*T, E] x [E, 4H]
  pair, r4 items 1-3): at measured fwd/dx rates — floor ~5.0 ms.
* scan-residual stacking: bf16 since r5; floor = bf16 bytes at the
  measured stream rate — ~0.85 ms.
* dense Adam on the two [30k, 512] tables: 856 GB/s measured whole-table
  stream; the floor prices the NAMED lever (lazy/sparse row Adam over
  gathered rows only) — ~0.55 ms.
* embedding scatter-add: scatter-rate bound — ~0.65 ms.

On-chip, ``--measure`` slope-times the real bench step next to the
floor-sum (the probe_tlm discipline: model-level slope is the stable
instrument); off-chip the analytic table stands alone. The final JSON
line carries the defended ceiling for BASELINE.md.

Usage: python tools/probe_s2s_ceiling.py [--measure]
"""
import json
import sys

sys.path.insert(0, ".")

#: per-term floors, milliseconds per bench step. Provenance: the r4
#: hlo_stats-attributed trace (docs/perf.md "Sequence workloads",
#: "remaining profile" paragraph) re-priced after the r5 bf16-stacking
#: win; "mechanism" names why the term cannot go below its floor from
#: above XLA (the r3/r5 precedent: in-kernel alternatives measured and
#: LOST — the flash ledger of negatives, the Pallas conv loss).
TERMS = [
    {"term": "head_matmuls", "floor_ms": 4.1,
     "r5_ms": 4.3, "mechanism": "MXU at measured 160-190 TF/s per shape "
     "(fwd/dx near peak; the dW share rides the r6 tuner verdict)"},
    {"term": "scan_bodies", "floor_ms": 3.2,
     "r5_ms": 3.5, "mechanism": "VMEM write bound ~2.4 TB/s, "
     "7-config measured local optimum (r4+r5 ledger)"},
    {"term": "gates_and_ce", "floor_ms": 5.0,
     "r5_ms": 5.2, "mechanism": "hoisted gate matmuls + CE statistic "
     "chains at measured per-shape rates (r4 items 1-3 already "
     "removed the layout copy and the f32 logits round-trip)"},
    {"term": "scan_stacking", "floor_ms": 0.85,
     "r5_ms": 0.9, "mechanism": "bf16 per-step output stacking at the "
     "measured stream rate (r5 halved it; the f32 carry is correctness)"},
    {"term": "optimizer", "floor_ms": 0.55,
     "r5_ms": 0.95, "mechanism": "NAMED HEADROOM: dense Adam streams "
     "both [30k,512] tables at 856 GB/s; a lazy row Adam touching only "
     "gathered rows is the one audited lever left"},
    {"term": "embedding_scatter", "floor_ms": 0.65,
     "r5_ms": 0.7, "mechanism": "scatter-add at measured scatter rates "
     "(device-side SelectedRows measured SLOWER at this table size)"},
]


def flops_per_step():
    """The bench's own analytic account (bench.bench_seq2seq)."""
    import bench

    e, h, v, t = bench.S2S_EMBED, bench.S2S_HIDDEN, bench.S2S_VOCAB, \
        bench.S2S_LEN
    fwd = 2 * bench.S2S_BATCH * t * (
        (e * 4 * h + h * 4 * h) + h * h
        + ((e + h) * 4 * h + h * 4 * h) + 2 * t * h + h * v)
    return 3 * fwd


def main():
    import bench

    total = flops_per_step()
    floor_ms = sum(t["floor_ms"] for t in TERMS)
    r5_ms = sum(t["r5_ms"] for t in TERMS)
    ceiling_mfu = total / (floor_ms / 1e3) / 1e12 / bench.PEAK_TFLOPS
    r5_mfu = total / (r5_ms / 1e3) / 1e12 / bench.PEAK_TFLOPS
    print(f"seq2seq bench step: {total / 1e9:.1f} GFLOP "
          f"(B={bench.S2S_BATCH} T={bench.S2S_LEN} H={bench.S2S_HIDDEN} "
          f"V={bench.S2S_VOCAB}), chip peak {bench.PEAK_TFLOPS} TF/s")
    print(f"{'term':<20}{'r5 ms':>8}{'floor ms':>10}  mechanism")
    for t in TERMS:
        print(f"{t['term']:<20}{t['r5_ms']:>8.2f}{t['floor_ms']:>10.2f}  "
              f"{t['mechanism']}")
    print(f"{'SUM':<20}{r5_ms:>8.2f}{floor_ms:>10.2f}")
    print(f"attributed r5 step {r5_ms:.2f} ms -> {r5_mfu:.1%} MFU "
          f"(measured r5: 15.53 ms, 33.6%)")
    print(f"defended ceiling: {floor_ms:.2f} ms -> {ceiling_mfu:.1%} MFU")
    measured = None
    if "--measure" in sys.argv:
        # the authoritative instrument: slope-time the real bench step
        run_step, fetch = bench.build_seq2seq(k=bench.PIPE_K)
        step_s, spread = bench._slope_time(run_step, fetch, warmup=3,
                                           iters=250, reps=5,
                                           steps_per_call=bench.PIPE_K)
        measured = {"step_ms": round(step_s * 1e3, 3),
                    "spread_ms": round(spread * 1e3, 3),
                    "mfu": round(total / step_s / 1e12
                                 / bench.PEAK_TFLOPS, 4)}
        print(f"measured: {measured['step_ms']} ms/step "
              f"({measured['mfu']:.1%} MFU, spread "
              f"{measured['spread_ms']} ms)")
    print(json.dumps({
        "workload": "seq2seq_nmt",
        "flops_per_step": total,
        "attributed_r5_ms": round(r5_ms, 2),
        "floor_sum_ms": round(floor_ms, 2),
        "defended_ceiling_mfu": round(ceiling_mfu, 4),
        "bar_mfu": 0.33,
        "terms": TERMS,
        "measured": measured,
    }))


if __name__ == "__main__":
    main()

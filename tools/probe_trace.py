"""Capture a device trace of a bench.py workload and print the audit.

Glue between bench.py's workload builders and the two audit views
(tools/hlo_audit.py per-fusion roofline, tools/trace_ops.py per-op type):
the traced program IS the benched program — both come from the same
bench.build_* function, so a config change in bench.py cannot
desynchronize the audit from the benchmark.

Usage: python tools/probe_trace.py {tlm,s2s,resnet,longcontext} [steps]
       [dir] [batch]   (batch override: tlm only)
"""
import glob
import os
import sys

sys.path.insert(0, ".")
import bench  # noqa: E402

BUILDERS = {
    "tlm": bench.build_transformer_lm,
    "s2s": bench.build_seq2seq,
    "resnet": bench.build_resnet,
    "longcontext": bench.build_longcontext_lm,
}


def main():
    workload = sys.argv[1] if len(sys.argv) > 1 else "tlm"
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    out = sys.argv[3] if len(sys.argv) > 3 else f"/tmp/trace_{workload}"
    import jax

    kw = {}
    if len(sys.argv) > 4:
        if workload != "tlm":
            raise SystemExit(
                f"batch override is only supported for tlm (the other "
                f"builders take no batch kwarg); got workload={workload}")
        kw["batch"] = int(sys.argv[4])
    run_step, fetch = BUILDERS[workload](**kw)
    for _ in range(3):
        run_step()
    fetch()
    jax.profiler.start_trace(out)
    for _ in range(steps - 1):
        run_step()
    fetch()
    jax.profiler.stop_trace()
    pbs = glob.glob(os.path.join(out, "**", "*.xplane.pb"), recursive=True)
    if not pbs:
        raise SystemExit(f"no *.xplane.pb produced under {out} — did the "
                         f"profiler run on this backend?")
    pb = max(pbs, key=os.path.getmtime)
    print(f"trace: {pb}\n")
    import hlo_audit
    import trace_ops

    hlo_audit.main(pb, steps=steps, top_n=40)
    print()
    trace_ops.main(pb, top_n=15)


if __name__ == "__main__":
    main()

"""`paddle`-style CLI (<- paddle/scripts/submit_local.sh.in: the `paddle`
wrapper exposing train/version subcommands around paddle_trainer).

Subcommands:
  train    — launch a local training run of a benchmark model
             (the paddle_trainer role; flags forward to the benchmark driver)
  version  — print framework/runtime versions
"""
from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def cmd_version():
    sys.path.insert(0, REPO)
    import jax

    import paddle_tpu

    print("paddle_tpu (TPU-native Paddle-capability framework)")
    print("  jax:", jax.__version__)
    try:
        platforms = sorted({d.platform for d in jax.devices()})
    except RuntimeError as e:  # no device/backend in this environment
        platforms = [f"unavailable ({e})"]
    print("  backends:", ", ".join(platforms))
    from paddle_tpu.core.registry import registered_ops

    print("  ops registered:", len(registered_ops()))


def cmd_train(argv):
    driver = os.path.join(REPO, "benchmark", "fluid_benchmark.py")
    os.execv(sys.executable, [sys.executable, driver] + argv)


def main():
    if len(sys.argv) < 2 or sys.argv[1] in ("-h", "--help", "help"):
        print(__doc__)
        print("usage: paddle_cli.py {train|version} [args...]")
        return 0
    sub = sys.argv[1]
    if sub == "version":
        cmd_version()
        return 0
    if sub == "train":
        cmd_train(sys.argv[2:])
        return 0  # unreachable (execv)
    print(f"unknown subcommand {sub!r}; use train|version")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())

"""`paddle`-style CLI (<- paddle/scripts/submit_local.sh.in: the `paddle`
wrapper exposing train/version subcommands around paddle_trainer).

Subcommands:
  train    — launch a local training run of a benchmark model
             (the paddle_trainer role; flags forward to the benchmark driver)
  version  — print framework/runtime versions
  trace    — summarize a Chrome-trace JSON (obs tracer / timeline.py
             output) without a browser: top spans by SELF time (child
             spans subtracted), per-stage duration histogram, slowest
             trace_ids. ``--convert OUT`` re-emits a normalized trace.
  fleet    — status table of serving replicas (health, queue, pipeline
             occupancy, MFU, weights version, derived circuit state)
             scraped from each endpoint's healthz + /metrics; endpoints
             as args or comma-separated. Unreachable replicas render as
             circuit=open.
  placement — run the parallelism placement searcher over an exported
             inference dir (serving/placement.py): prints the scored
             (dp, tp) candidate table and the chosen PlacementPlan
             (splits, predicted comm bytes/step, per-device HBM).
             NONZERO exit when no plan fits the modeled HBM — the
             must-shard signal a deploy script can gate on.
  doctor   — reconstruct an incident from a flight-recorder postmortem
             bundle (obs/flight.py): schema validation, the event
             timeline (events joined with span exemplars and SLO
             breaches via trace ids), dominant-stage/replica
             attribution, and suspect-ranked findings. ``--replay``
             re-runs the bundle's captured predict/generate requests
             against fresh engines and verifies bit-identical outputs.
             Exit 2 on a schema-invalid bundle, 1 on replay mismatch.
  replay   — just the replay harness over a bundle's captures.
  tune     — inspect a persistent kernel-tuning DB (paddle_tpu/tune,
             docs/design.md §21): one row per entry (op, shape, dtype,
             decision, chosen config, measured margin, age, staleness on
             this backend/runtime) plus the adopted/rejected/stale
             census. ``--prune-stale`` drops backend/runtime-mismatched
             entries and saves. Exit 2 on a corrupt or schema-mismatched
             file (the typed TuningDBError refusal).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
from collections import defaultdict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _probe_backends(timeout_s=None):
    """Platform list via a killable child: `version` is a host-side
    informational command, and an accelerator plugin probing absent
    hardware can hang jax backend init for minutes (the PR-1 benchmark
    driver hang) — that must bound-fail the backends line, not the CLI.
    PADDLE_CLI_PROBE_TIMEOUT_S overrides the bound (CI on plugin-less
    hosts pays the full timeout just to print "unavailable")."""
    if timeout_s is None:
        timeout_s = float(os.environ.get("PADDLE_CLI_PROBE_TIMEOUT_S", "45"))
    code = ("import jax; "
            "print(','.join(sorted({d.platform for d in jax.devices()})))")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, cwd=REPO,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return ["unavailable (backend probe timed out)"]
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return [f"unavailable ({tail[-1] if tail else r.returncode})"]
    return r.stdout.strip().split(",")


def cmd_version():
    sys.path.insert(0, REPO)
    import jax

    import paddle_tpu

    print("paddle_tpu (TPU-native Paddle-capability framework)")
    print("  jax:", jax.__version__)
    print("  backends:", ", ".join(_probe_backends()))
    from paddle_tpu.core.registry import registered_ops

    print("  ops registered:", len(registered_ops()))


def cmd_train(argv):
    driver = os.path.join(REPO, "benchmark", "fluid_benchmark.py")
    os.execv(sys.executable, [sys.executable, driver] + argv)


# -- trace inspection ------------------------------------------------------
_HIST_BUCKETS_MS = (0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                    1000, float("inf"))


def load_trace(path):
    """Chrome-trace JSON -> list of complete ('X') event dicts."""
    with open(path) as f:
        obj = json.load(f)
    events = obj.get("traceEvents", obj) if isinstance(obj, dict) else obj
    return [e for e in events if e.get("ph") == "X"]


def self_times(events):
    """name -> (count, total_us, self_us). Children are detected by strict
    time containment on the same (pid, tid) lane — works on any Chrome
    trace, not just ones carrying explicit parent links."""
    by_lane = defaultdict(list)
    for e in events:
        by_lane[(e.get("pid", 0), e.get("tid", 0))].append(e)
    agg = defaultdict(lambda: [0, 0.0, 0.0])  # count, total, self
    for lane in by_lane.values():
        lane.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
        stack = []  # (end_ts, event, child_total)
        def pop_until(ts):
            while stack and stack[-1][0] <= ts + 1e-9:
                end, ev, child = stack.pop()
                rec = agg[ev["name"]]
                rec[0] += 1
                rec[1] += ev.get("dur", 0.0)
                rec[2] += max(ev.get("dur", 0.0) - child, 0.0)
                if stack:
                    stack[-1][2] += ev.get("dur", 0.0)
        for e in lane:
            pop_until(e["ts"])
            stack.append([e["ts"] + e.get("dur", 0.0), e, 0.0])
        pop_until(float("inf"))
    return {n: tuple(v) for n, v in agg.items()}


def stage_histogram(events):
    """name -> per-_HIST_BUCKETS_MS counts of span durations."""
    hist = defaultdict(lambda: [0] * len(_HIST_BUCKETS_MS))
    for e in events:
        ms = e.get("dur", 0.0) / 1e3
        for i, b in enumerate(_HIST_BUCKETS_MS):
            if ms <= b:
                hist[e["name"]][i] += 1
                break
    return dict(hist)


def trace_report(events, top=15):
    """Human-readable summary (also what tests assert against)."""
    lines = []
    st = sorted(self_times(events).items(), key=lambda kv: -kv[1][2])
    lines.append(f"{'span':<38}{'calls':>7}{'total_ms':>12}{'self_ms':>12}")
    for name, (count, total, self_us) in st[:top]:
        lines.append(f"{name:<38}{count:>7}{total / 1e3:>12.3f}"
                     f"{self_us / 1e3:>12.3f}")
    hist = stage_histogram(events)
    lines.append("")
    lines.append("stage histogram (span count per duration bucket, ms):")
    labels = [("<=" + (f"{b:g}" if b != float("inf") else "inf"))
              for b in _HIST_BUCKETS_MS]
    for name in sorted(hist):
        nz = [(l, c) for l, c in zip(labels, hist[name]) if c]
        lines.append(f"  {name}: " + " ".join(f"{l}:{c}" for l, c in nz))
    slow = sorted((e for e in events
                   if e.get("args", {}).get("trace_id")),
                  key=lambda e: -e.get("dur", 0.0))
    if slow:
        lines.append("")
        lines.append("slowest traced requests:")
        for e in slow[:5]:
            lines.append(f"  {e['args']['trace_id']}  {e['name']}  "
                         f"{e.get('dur', 0.0) / 1e3:.3f}ms")
    return "\n".join(lines)


def cmd_trace(argv):
    import argparse

    ap = argparse.ArgumentParser(
        prog="paddle_cli.py trace",
        description="summarize/convert a Chrome-trace JSON")
    ap.add_argument("path", help="trace file (obs dump / timeline.py out)")
    ap.add_argument("--top", type=int, default=15,
                    help="rows in the self-time table")
    ap.add_argument("--convert", metavar="OUT",
                    help="also write a normalized pretty-printed trace")
    args = ap.parse_args(argv)
    events = load_trace(args.path)
    if not events:
        print(f"{args.path}: no complete ('X') trace events")
        return 1
    print(f"{args.path}: {len(events)} spans")
    print(trace_report(events, top=args.top))
    if args.convert:
        with open(args.convert, "w") as f:
            json.dump({"traceEvents": events}, f, indent=2)
        print(f"normalized trace written to {args.convert}")
    return 0


# -- fleet status ----------------------------------------------------------


def fleet_rows(endpoints, timeout=3.0):
    """Scrape each replica's healthz + metrics; one status dict per
    endpoint. The circuit column is DERIVED: an endpoint that cannot be
    scraped is what a router's breaker would hold open."""
    sys.path.insert(0, REPO)
    from paddle_tpu.serving import ServingClient
    from paddle_tpu.serving.fleet import scraped_gauges

    rows = []
    for ep in endpoints:
        row = {"endpoint": ep, "health": "unreachable", "circuit": "open",
               "queue": "-", "capacity": "-", "occupancy": "-", "mfu": "-",
               "shards": "-", "weights": "-", "quant": "-", "kv": "-",
               "goodput": "-", "accept": "-", "hbm": "-", "unattr": "-",
               "kvshare": "-", "decode": ""}
        try:
            with ServingClient(ep, timeout=timeout) as c:
                hz = c.healthz()
                m = scraped_gauges(hz, c.metrics())
            from paddle_tpu.serving.quant import QUANT_MODE_NAMES

            row.update(
                health=hz.get("state", "?"), circuit="closed",
                queue=int(m["queue_depth"]),
                capacity=int(m["queue_capacity"]),
                occupancy=int(m["occupancy"]),
                mfu=m["mfu"],
                shards=int(m.get("shards", 1)),
                quant=QUANT_MODE_NAMES.get(int(m.get("quant_mode", 0)),
                                           "f32"),
                weights=int(m["weights_version"]),
                # goodput accounting (docs §23): windowed good/(good+bad)
                # request-seconds; 1.0 = neutral (not accounting / idle)
                goodput=f"{m.get('goodput_ratio', 1.0):.2f}")
            # speculative-decode acceptance (docs §25): lifetime
            # accepted/proposed; the gauge idles at -1.0 until the
            # replica's first draft proposal ("-" = spec never armed)
            acc = float(m.get("spec_acceptance", -1.0))
            if acc >= 0.0:
                row["accept"] = f"{acc:.0%}"
            # paged-KV column: in-use/total pages + prefix-cache hit rate
            # (the session-affinity signal; "-" on unpaged replicas)
            total_pg = int(m.get("kv_pages_free", 0)
                           + m.get("kv_pages_active", 0)
                           + m.get("kv_pages_cached", 0))
            if total_pg:
                used = int(m["kv_pages_active"] + m["kv_pages_cached"])
                row["kv"] = (f"{used}/{total_pg}pg "
                             f"{m.get('prefix_hit_rate', 0.0):.0%}")
            # memory-ledger columns (docs §28): measured HBM occupancy
            # against the replica's declared capacity, live bytes no
            # component claimed (the reconciliation gap), and the KV
            # pool's share of tracked bytes ("-" = no ledger/capacity)
            occ_hbm = float(m.get("hbm_occupancy", 0.0))
            if occ_hbm > 0.0:
                row["hbm"] = f"{occ_hbm:.0%}"
            unattr = float(m.get("mem_unattributed", 0.0))
            if unattr > 0.0:
                row["unattr"] = f"{unattr / 2**20:.1f}M"
            share = float(m.get("kv_pool_share", 0.0))
            if share > 0.0:
                row["kvshare"] = f"{share:.0%}"
            d = hz.get("decode")
            if d:
                row["decode"] = (f"{d['active_slots']}/{d['max_slots']} "
                                 f"slots")
        except Exception:
            pass
        rows.append(row)
    return rows


def router_summary(endpoint, timeout=3.0):
    """Scrape a FleetRouter's own HTTP /metrics + /healthz (the router
    satellite: FleetRouter(metrics_port=...)) into one status dict."""
    import json as _json
    import urllib.request

    sys.path.insert(0, REPO)
    from paddle_tpu.serving.fleet import parse_prometheus_gauges

    out = {"endpoint": endpoint, "reachable": False}
    try:
        hz = _json.loads(urllib.request.urlopen(
            f"http://{endpoint}/healthz", timeout=timeout).read().decode())
        text = urllib.request.urlopen(
            f"http://{endpoint}/metrics", timeout=timeout).read().decode()
        g = parse_prometheus_gauges(text)
    except Exception:
        return out
    # pt_fleet_failovers_total is labeled by op — parse_prometheus_gauges
    # keeps only the first sample per family, so sum the series by hand
    failovers = 0.0
    for line in text.splitlines():
        if line.startswith("pt_fleet_failovers_total{"):
            try:
                failovers += float(line.rsplit(None, 1)[1])
            except (IndexError, ValueError):
                pass
    out.update(
        reachable=True, state=hz.get("state", "?"),
        replicas=int(g.get("pt_fleet_replicas", 0)),
        healthy=int(g.get("pt_fleet_healthy_replicas", 0)),
        pressure=g.get("pt_fleet_pressure", 0.0),
        qps_per_replica=g.get("pt_fleet_qps_per_replica", 0.0),
        hedges=int(g.get("pt_fleet_hedges_total", 0)),
        failovers=int(failovers),
        circuit_opens=int(g.get("pt_fleet_circuit_open_total", 0)))
    return out


def router_report(r):
    if not r.get("reachable"):
        return f"router {r['endpoint']}: UNREACHABLE"
    return (f"router {r['endpoint']}: state={r['state']} "
            f"replicas={r['healthy']}/{r['replicas']} healthy  "
            f"pressure={r['pressure']:.2f}  "
            f"qps/replica={r['qps_per_replica']:.1f}  "
            f"hedges={r['hedges']} failovers={r['failovers']} "
            f"circuit_opens={r['circuit_opens']}")


def fleet_report(rows):
    lines = [f"{'replica':<24}{'health':<12}{'circuit':<9}{'queue':>9}"
             f"{'occ':>5}{'mfu':>11}{'shards':>7}{'quant':>7}"
             f"{'weights':>9}{'kv':>15}{'goodput':>9}{'accept':>8}"
             f"{'hbm':>6}{'unattr':>9}{'kvshare':>9}  decode"]
    for r in rows:
        q = (f"{r['queue']}/{r['capacity']}"
             if r["queue"] != "-" else "-")
        mfu = f"{r['mfu']:.2e}" if r["mfu"] != "-" else "-"
        lines.append(f"{r['endpoint']:<24}{r['health']:<12}"
                     f"{r['circuit']:<9}{q:>9}{str(r['occupancy']):>5}"
                     f"{mfu:>11}{str(r.get('shards', '-')):>7}"
                     f"{str(r.get('quant', '-')):>7}"
                     f"{str(r['weights']):>9}"
                     f"{str(r.get('kv', '-')):>15}"
                     f"{str(r.get('goodput', '-')):>9}"
                     f"{str(r.get('accept', '-')):>8}"
                     f"{str(r.get('hbm', '-')):>6}"
                     f"{str(r.get('unattr', '-')):>9}"
                     f"{str(r.get('kvshare', '-')):>9}  {r['decode']}")
    healthy = sum(1 for r in rows if r["health"] == "healthy")
    lines.append(f"{healthy}/{len(rows)} replicas healthy")
    return "\n".join(lines)


def cmd_fleet(argv):
    import argparse

    ap = argparse.ArgumentParser(
        prog="paddle_cli.py fleet",
        description="status table of serving replicas from scraped "
                    "healthz + /metrics")
    ap.add_argument("endpoints", nargs="+",
                    help="replica endpoints (host:port, space- or "
                         "comma-separated)")
    ap.add_argument("--timeout", type=float, default=3.0,
                    help="per-replica scrape timeout (s)")
    ap.add_argument("--router", metavar="HOST:PORT", default=None,
                    help="also scrape a FleetRouter's own HTTP metrics "
                         "endpoint (FleetRouter(metrics_port=...)) and "
                         "print the router-level gauges above the table")
    args = ap.parse_args(argv)
    router_ok = True
    if args.router:
        r = router_summary(args.router, timeout=args.timeout)
        print(router_report(r))
        router_ok = bool(r.get("reachable")) \
            and r.get("state") == "healthy"
    eps = [e for spec in args.endpoints for e in spec.split(",") if e]
    rows = fleet_rows(eps, timeout=args.timeout)
    print(fleet_report(rows))
    return 0 if router_ok \
        and all(r["health"] == "healthy" for r in rows) else 1


# -- postmortem doctor -----------------------------------------------------


def _fmt_attrs(attrs, limit=4):
    if not attrs:
        return ""
    items = list(attrs.items())[:limit]
    s = " ".join(f"{k}={v}" for k, v in items)
    return s if len(s) <= 76 else s[:73] + "..."


def _exemplar_stage_totals(bundle):
    """stage/span name -> total ms across the bundle's span exemplars."""
    totals = {}
    for ex in bundle.get("exemplars") or []:
        for sp in ex.get("spans") or []:
            name = sp.get("name", "?")
            dur = sp.get("dur_ms")
            if dur is None:
                dur = sp.get("dur", 0.0) * 1e3
            totals[name] = totals.get(name, 0.0) + float(dur)
    return totals


def doctor_findings(bundle):
    """Suspect-ranked findings: [(score, text)] most-suspect first.
    Heuristics over the joined evidence: error events dominate, then
    chaos/warn activity per replica, NaN sentinels, SLO breaches, and the
    dominant stage of the retained p99 exemplars."""
    events = bundle.get("events") or []
    findings = []
    # chaos injections aggregate across ALL severities (faults are warn,
    # heals like restarts are info — the harness's activity is one story)
    faults = {}
    for e in events:
        if e.get("type") == "chaos_inject":
            f = (e.get("attrs") or {}).get("fault", "?")
            faults[f] = faults.get(f, 0) + 1
    if faults:
        findings.append((3 * sum(faults.values()),
                         f"chaos harness injected "
                         f"{sum(faults.values())} faults: "
                         + ", ".join(f"{k} x{v}"
                                     for k, v in sorted(faults.items()))))
    # typed error/warn events grouped by (type, replica)
    by_key = {}
    for e in events:
        if e.get("severity") not in ("warn", "error") \
                or e.get("type") == "chaos_inject":
            continue
        attrs = e.get("attrs") or {}
        key = (e.get("type"), attrs.get("replica") or attrs.get("endpoint"))
        by_key.setdefault(key, []).append(e)
    for (typ, rep), evs in by_key.items():
        sev = any(x.get("severity") == "error" for x in evs)
        score = len(evs) * (10 if sev else 3)
        where = f" on {rep}" if rep else ""
        if typ == "nan_detected":
            steps = sorted(x.get("step") for x in evs
                           if x.get("step") is not None)
            findings.append((score * 5, f"training numerics: NaN at "
                             f"step(s) {steps[:5]} — see the captured "
                             f"metrics/flags for the config that produced "
                             f"it"))
        elif typ == "rollback":
            windows = sorted({(x.get("attrs") or {}).get("window")
                              for x in evs} - {None})
            serials = sorted({(x.get("attrs") or {}).get("restored_serial")
                              for x in evs} - {None})
            skipped = sum(1 for x in evs
                          if (x.get("attrs") or {}).get("skip"))
            tail = (f"; {skipped} window(s) ultimately SKIPPED "
                    f"(poisoned data, stamped in the cursor)"
                    if skipped else "")
            findings.append((score * 3, f"resilience: {len(evs)} "
                             f"rollback(s) to snapshot serial(s) "
                             f"{serials[:5]} at window(s) {windows[:5]}"
                             f"{tail} — the nan_detected/chaos findings "
                             f"name the trigger"))
        elif typ == "preemption":
            serials = sorted({(x.get("attrs") or {}).get("serial")
                              for x in evs} - {None})
            findings.append((score, f"resilience: preemption drained "
                             f"with grace snapshot serial(s) "
                             f"{serials[:5]} — the resumed run continues "
                             f"bit-exactly from there"))
        elif typ == "oom":
            # memory postmortem (docs §28): the ledger snapshot rode the
            # bundle (mem_ledger provider) — rank the component holding
            # the most HBM at failure, and if the model-drift findings
            # put it above its analytic plan, say by how much
            mem = (bundle.get("providers") or {}).get("mem_ledger") or {}
            mtotals = mem.get("totals") or {}
            dev = float(mem.get("device_bytes") or 0.0) \
                or float(sum(mtotals.values()))
            comps = sorted({(x.get("attrs") or {}).get("component")
                            for x in evs} - {None})
            text = (f"OOM: {len(evs)} RESOURCE_EXHAUSTED dispatch(es)"
                    + (f" at {', '.join(comps)}" if comps else ""))
            if mtotals and dev > 0:
                suspect, nbytes = max(mtotals.items(), key=lambda kv: kv[1])
                text += (f" — suspect {suspect}: {nbytes / dev:.0%} of "
                         f"tracked HBM at failure "
                         f"({nbytes / 2**30:.2f} GiB)")
                for d in mem.get("drift") or []:
                    if d.get("component") == suspect \
                            and not d.get("within_tolerance"):
                        over = (float(d.get("measured_bytes", 0.0))
                                - float(d.get("planned_bytes", 0.0)))
                        if over > 0:
                            text += (f", {over / 2**30:.2f} GiB above "
                                     f"the placement plan")
            unattr = float((mem.get("reconcile") or {})
                           .get("unattributed_bytes", 0.0) or 0.0)
            if unattr > 0:
                text += (f"; {unattr / 2**20:.1f} MiB live but "
                         f"unattributed (possible leak)")
            findings.append((score * 6, text))
        elif typ == "slo_breach":
            slos = {}
            for x in evs:
                s = (x.get("attrs") or {}).get("slo", "?")
                slos[s] = slos.get(s, 0) + 1
            findings.append((score * 2, "SLO burn: "
                             + ", ".join(f"{k} breached x{v}"
                                         for k, v in sorted(slos.items()))))
        else:
            findings.append((score, f"{len(evs)} x {typ}{where}"))
    # 2) dominant stage across exemplar span lists
    totals = _exemplar_stage_totals(bundle)
    if totals:
        total = sum(totals.values())
        stage, ms = max(totals.items(), key=lambda kv: kv[1])
        if total > 0:
            findings.append((int(ms), f"dominant stage across p99 "
                             f"exemplars: {stage} "
                             f"({ms / total:.0%} of retained span time)"))
    # 3) differential attribution (docs §23): when the bundle carries a
    # profile pair, the goodput provider's diff NAMES the owning category
    # — rank it right with the evidence instead of leaving it to a human
    gp = (bundle.get("providers") or {}).get("goodput")
    attributed = False
    if isinstance(gp, dict):
        diff = gp.get("diff")
        if not isinstance(diff, dict) and gp.get("profiles") \
                and len(gp["profiles"]) >= 2:
            # a bundle carrying the raw profile pair but no precomputed
            # diff: run the attributor here
            try:
                sys.path.insert(0, REPO)
                from paddle_tpu.obs.profile import diff_profiles

                diff = diff_profiles(gp["profiles"][-2], gp["profiles"][-1])
            except Exception:
                diff = None
        if isinstance(diff, dict) and diff.get("owners"):
            attributed = True
            findings.append((
                40 if diff.get("regressed") else 5,
                f"goodput attribution: {diff.get('summary')}"
                + ("" if diff.get("regressed") else " (within tolerance)")))
    if not attributed:
        # perf_regression events carry the attributor's verdict even when
        # the provider snapshot is absent — restate the summary so the
        # owning category is named in the findings
        for e in events:
            if e.get("type") == "perf_regression":
                attrs = e.get("attrs") or {}
                if attrs.get("summary"):
                    findings.append(
                        (35, f"perf regression: {attrs['summary']}"))
    # 4) dropped events = incomplete evidence
    if bundle.get("events_dropped"):
        findings.append((1, f"event ring dropped "
                         f"{bundle['events_dropped']} events — raise "
                         f"obs_events_capacity for complete postmortems"))
    findings.sort(key=lambda f: -f[0])
    return findings


def doctor_report(bundle, top=40):
    """(report_text, findings, schema_problems) — the testable core of
    ``cmd_doctor``."""
    sys.path.insert(0, REPO)
    from paddle_tpu.obs.flight import validate_bundle

    problems = validate_bundle(bundle)
    lines = []
    trig = bundle.get("trigger") or {}
    lines.append(f"postmortem bundle schema v{bundle.get('schema_version')} "
                 f"— trigger: {trig.get('type', '?')} "
                 f"{_fmt_attrs({k: v for k, v in trig.items() if k != 'type'})}")
    if problems:
        lines.append("SCHEMA INVALID:")
        lines.extend(f"  - {p}" for p in problems)
    else:
        lines.append("schema: valid")
    events = sorted(bundle.get("events") or [], key=lambda e: e.get("t", 0))
    lines.append(f"events: {len(events)} retained, "
                 f"{bundle.get('events_dropped', 0)} dropped; counts: "
                 + (", ".join(f"{k}={v}" for k, v in
                              sorted((bundle.get('event_counts')
                                      or {}).items())) or "none"))
    if events:
        t0 = events[0].get("t", 0.0)
        lines.append("")
        lines.append("incident timeline (relative seconds):")
        shown = events if len(events) <= top else events[-top:]
        if len(events) > top:
            lines.append(f"  ... {len(events) - top} earlier events elided "
                         f"(--top)")
        for e in shown:
            tid = f"  [{e['trace_id']}]" if e.get("trace_id") else ""
            step = f" step={e['step']}" if e.get("step") is not None else ""
            lines.append(f"  +{e.get('t', 0.0) - t0:8.3f}s "
                         f"{e.get('severity', '?'):<5} "
                         f"{e.get('type', '?'):<22}"
                         f"{_fmt_attrs(e.get('attrs'))}{step}{tid}")
    # events <-> exemplar spans join by trace id
    ex_keys = {ex.get("key") for ex in bundle.get("exemplars") or []}
    linked = sorted({e["trace_id"] for e in events
                     if e.get("trace_id") in ex_keys})
    if linked:
        lines.append("")
        lines.append(f"traces linked to retained span exemplars: "
                     f"{', '.join(linked[:8])}")
    breaches = [e for e in events if e.get("type") == "slo_breach"]
    if breaches:
        lines.append("")
        lines.append("SLO breaches:")
        for e in breaches[:10]:
            lines.append(f"  {_fmt_attrs(e.get('attrs'))}")
    slo_prov = (bundle.get("providers") or {}).get("slo")
    if isinstance(slo_prov, dict) and slo_prov.get("breaches"):
        lines.append(f"watchdog totals: {slo_prov['breaches']} over "
                     f"{slo_prov.get('evals')} evaluations")
    findings = doctor_findings(bundle)
    lines.append("")
    lines.append("suspect-ranked findings:")
    if findings:
        for i, (score, text) in enumerate(findings[:10], 1):
            lines.append(f"  {i}. [{score:>5}] {text}")
    else:
        lines.append("  (no warn/error evidence — quiet bundle)")
    caps = bundle.get("captures") or []
    lines.append("")
    lines.append(f"captured requests: {len(caps)} "
                 f"({sum(1 for c in caps if c.get('kind') == 'predict')} "
                 f"predict, "
                 f"{sum(1 for c in caps if c.get('kind') == 'generate')} "
                 f"generate) — replay with `paddle_cli.py doctor --replay`")
    return "\n".join(lines), findings, problems


def _print_replay(results):
    ok = True
    for r in results:
        # ok=None = skipped (digest-only capture): reported, not a failure
        ok &= r.get("ok") is not False
        flag = {True: "OK  ", False: "FAIL", None: "SKIP"}[r.get("ok")]
        print(f"  capture #{r.get('id')} {r.get('kind'):<9} "
              f"{flag} {r.get('detail')}")
    n_ok = sum(1 for r in results if r.get("ok"))
    n_skip = sum(1 for r in results if r.get("ok") is None)
    tail = f" ({n_skip} skipped)" if n_skip else ""
    print(f"replay: {n_ok}/{len(results) - n_skip} bit-identical{tail}")
    return ok


def cmd_doctor(argv):
    import argparse

    ap = argparse.ArgumentParser(
        prog="paddle_cli.py doctor",
        description="reconstruct an incident from a flight-recorder "
                    "postmortem bundle")
    ap.add_argument("bundle", help="bundle JSON (FlightRecorder.dump)")
    ap.add_argument("--top", type=int, default=40,
                    help="timeline rows to print")
    ap.add_argument("--replay", action="store_true",
                    help="re-run the captured requests and verify "
                         "bit-identical outputs")
    args = ap.parse_args(argv)
    sys.path.insert(0, REPO)
    from paddle_tpu.obs.flight import load_bundle, replay_bundle

    bundle = load_bundle(args.bundle)
    text, _findings, problems = doctor_report(bundle, top=args.top)
    print(text)
    if problems:
        return 2
    if args.replay:
        results = replay_bundle(bundle)
        if results:
            if not _print_replay(results):
                return 1
        else:
            print("replay: no captures in the bundle")
    return 0


def cmd_replay(argv):
    import argparse

    ap = argparse.ArgumentParser(
        prog="paddle_cli.py replay",
        description="re-run a bundle's captured requests against fresh "
                    "engines; verify bit-identical outputs")
    ap.add_argument("bundle")
    ap.add_argument("--model-dir", default=None,
                    help="override the captures' recorded export dir")
    args = ap.parse_args(argv)
    sys.path.insert(0, REPO)
    from paddle_tpu.obs.flight import load_bundle, replay_bundle

    results = replay_bundle(load_bundle(args.bundle),
                            model_dir=args.model_dir)
    if not results:
        print("no captures in the bundle")
        return 0
    return 0 if _print_replay(results) else 1


# -- tuning DB inspection --------------------------------------------------


def _fmt_age(seconds):
    if seconds < 120:
        return f"{seconds:.0f}s"
    if seconds < 7200:
        return f"{seconds / 60:.0f}m"
    if seconds < 172800:
        return f"{seconds / 3600:.1f}h"
    return f"{seconds / 86400:.1f}d"


def tune_report(db_path, prune_stale=False):
    """Render the tuning DB as a table: one row per entry (key fields,
    decision, chosen config, measured margin, age, staleness on THIS
    backend/runtime). ``prune_stale`` drops the backend/runtime-mismatched
    entries and persists. Raises ``TuningDBError`` (schema mismatch /
    corrupt file) for ``cmd_tune`` to turn into a nonzero exit."""
    import time as _time

    sys.path.insert(0, REPO)
    from paddle_tpu import tune

    db = tune.TuningDB(db_path)
    pruned = 0
    if prune_stale:
        pruned = db.prune_stale()
        if pruned and db.path:
            db.save(merge=False)  # publish the deletion, don't resurrect
            mdir = os.path.dirname(os.path.abspath(db_path))
            if os.path.exists(os.path.join(mdir, "_MANIFEST.json")):
                # pruning a checkpoint's bundled tuned.json rewrote a
                # digest-covered file — refresh the manifest (the
                # reshard_sharded_var discipline) or the valid checkpoint
                # would read as corrupt at the next load
                from paddle_tpu import io as pt_io

                pt_io.write_checkpoint_manifest(mdir)
    now = _time.time()
    header = (f"{'op':<18}{'shape':<18}{'dtype':<10}{'decision':<9}"
              f"{'config':<34}{'margin':>7}{'age':>7}  stale?")
    lines = [header, "-" * len(header)]
    n_adopt = n_reject = n_stale = 0
    for _key, ent in db.items():
        stale = db.is_stale(ent)
        n_stale += stale
        n_adopt += ent["decision"] == "adopt"
        n_reject += ent["decision"] == "reject"
        cfg = ent.get("config")
        if ent["decision"] == "reject" or not cfg:
            cfg_s = "stock"
        else:
            cfg_s = ",".join(f"{k}={v}" for k, v in sorted(cfg.items())
                             if v is not None)
        margin = ent.get("margin")
        lines.append(
            f"{ent['op']:<18}"
            f"{'x'.join(str(s) for s in ent['shape']):<18}"
            f"{ent['dtype']:<10}{ent['decision']:<9}{cfg_s[:33]:<34}"
            f"{margin if margin is not None else '-':>7}"
            f"{_fmt_age(max(0.0, now - ent.get('updated_at', 0.0))):>7}"
            f"  {'STALE (' + ent['backend'] + '/' + ent['runtime'] + ')' if stale else '-'}")
    lines.append(f"{len(db)} entries ({n_adopt} adopted, {n_reject} "
                 f"rejected, {n_stale} stale) — schema "
                 f"{tune.SCHEMA_VERSION}, backend "
                 f"{tune.backend_signature()}/{tune.runtime_signature()}")
    if prune_stale:
        lines.append(f"pruned {pruned} stale entries")
    return "\n".join(lines), db


def cmd_tune(argv):
    import argparse

    ap = argparse.ArgumentParser(
        prog="paddle_cli.py tune",
        description="inspect a persistent kernel-tuning DB "
                    "(docs/design.md §21); nonzero exit on a corrupt or "
                    "schema-mismatched file")
    ap.add_argument("db", help="TuningDB path (or a bundled tuned.json)")
    ap.add_argument("--prune-stale", action="store_true",
                    help="drop backend/runtime-mismatched entries and save")
    args = ap.parse_args(argv)
    sys.path.insert(0, REPO)
    from paddle_tpu.tune import TuningDBError

    if not os.path.exists(args.db):
        print(f"no tuning DB at {args.db!r}", file=sys.stderr)
        return 2
    try:
        report, _db = tune_report(args.db, prune_stale=args.prune_stale)
    except TuningDBError as e:
        print(f"tuning DB refused: {e}", file=sys.stderr)
        return 2
    print(report)
    return 0


# -- placement search ------------------------------------------------------


def _parse_batch_mix(spec):
    """"1:0.7,8:0.3" -> [(1, 0.7), (8, 0.3)]."""
    out = []
    for part in spec.split(","):
        rows, _, weight = part.partition(":")
        out.append((int(rows), float(weight or 1.0)))
    return out


def train_placement_report(prof, chips=8, hbm_gb=16.0, peak_tflops=197.0,
                           hbm_gbps=820.0, link_gbps=45.0,
                           global_batch=64, optimizer="adam"):
    """(report_text, chosen_plan_or_None) — the TRAINING placement table
    (docs §24): every (dp, accum_steps, zero_stage) split of the global
    batch scored under the ZeRO byte account and the ring-collective
    step-time model. ``prof`` is the serving ``ModelProfile`` the export
    walk already produced — the training profile derives from it (same
    params; f32 grads; optimizer-state multiplier by optimizer type)."""
    sys.path.insert(0, REPO)
    from paddle_tpu.placement import (DeviceInventory, NoFeasiblePlacement,
                                      TrainProfile, TrainPlacementSearcher,
                                      train_plan_table)

    cfg = prof.cfg
    # measured element count off the real export; the cost formulas are
    # TrainProfile.for_lm's — ONE owner, shared with the searcher grid
    tprof = TrainProfile.for_lm(
        prof.param_bytes / prof.dtype_bytes, cfg["n_layers"],
        cfg["d_model"], cfg["d_ff"], cfg["vocab"], cfg["max_len"],
        optimizer=optimizer, source=prof.source)
    inv = DeviceInventory(chips, hbm_gb=hbm_gb, peak_tflops=peak_tflops,
                          hbm_gbps=hbm_gbps, link_gbps=link_gbps)
    searcher = TrainPlacementSearcher(tprof, inv, global_batch)
    mult = tprof.opt_state_bytes / tprof.param_bytes
    lines = [f"--- train plan table (global batch {global_batch}, "
             f"{optimizer}: params + {mult:.0f}x opt state) ---",
             train_plan_table(searcher.all_plans())]
    try:
        best = searcher.search()
    except NoFeasiblePlacement as e:
        lines.append(f"train: NO FEASIBLE PLAN: {e}")
        return "\n".join(lines), None
    sched = f" sched={best.pp_schedule}" if best.pp > 1 else ""
    lines.append(
        f"train chosen: dp={best.dp} tp={best.tp} pp={best.pp} "
        f"accum={best.accum_steps} zero={best.zero_stage}"
        f"{sched}  per-device HBM "
        f"{best.hbm_bytes_per_device / 2**30:.3f} GiB "
        f"({best.hbm_fraction:.0%})  comm "
        f"{best.comm_bytes_per_step / 2**20:.2f} MiB/step over "
        f"{best.collectives_per_step} collectives  modeled step "
        f"{best.step_s * 1e3:.2f} ms "
        f"({best.rows_per_sec_per_chip:.1f} rows/s/chip, "
        f"overlap can hide {best.overlap_frac:.0%} of comm)")
    return "\n".join(lines), best


def placement_report(dirname, chips=8, hbm_gb=16.0, peak_tflops=197.0,
                     hbm_gbps=820.0, link_gbps=45.0, batch_mix="1:0.7,8:0.3",
                     p95_ms=None, seq_len=None, decode_slots=0,
                     quantize=None, train_chips=None, train_batch=64,
                     train_optimizer="adam"):
    """(report_text, chosen_plan_or_None) — the testable core of
    ``cmd_placement``. With ``quantize`` the f32 and quantized byte
    accounts are searched SIDE BY SIDE (the headline row: a model that
    must-shard at f32 but fits one chip under int8 — the quantized store
    is ~1/4 the HBM); the returned plan is the QUANTIZED one. With
    ``train_chips`` the TRAINING (dp, accum_steps, zero_stage) table
    prints next to the serving one; when the train search finds nothing
    the report carries its NO FEASIBLE PLAN line and the returned plan
    is ``None`` (the nonzero-exit signal)."""
    sys.path.insert(0, REPO)
    from paddle_tpu.serving.placement import (DeviceInventory,
                                              NoFeasiblePlacement,
                                              PlacementSearcher,
                                              TrafficProfile, plan_table,
                                              profile_export)

    prof = profile_export(dirname)
    inv = DeviceInventory(chips, hbm_gb=hbm_gb, peak_tflops=peak_tflops,
                          hbm_gbps=hbm_gbps, link_gbps=link_gbps)
    traffic = TrafficProfile(_parse_batch_mix(batch_mix), seq_len=seq_len,
                             p95_budget_ms=p95_ms, decode_slots=decode_slots)
    lines = [f"{dirname}: {prof.cfg['n_layers']}L x d{prof.cfg['d_model']} "
             f"x ff{prof.cfg['d_ff']} x V{prof.cfg['vocab']} "
             f"({prof.param_bytes / 2**30:.3f} GiB params, "
             f"xla_flops/row={prof.xla_flops})",
             f"inventory: {chips} x {hbm_gb} GiB @ {peak_tflops} TFLOP/s, "
             f"link {link_gbps} GB/s"]
    profiles = [("f32", prof)]
    if quantize:
        qprof = prof.quantize(quantize)
        lines.append(
            f"quantized ({quantize}): params "
            f"{qprof.param_bytes / 2**30:.3f} GiB "
            f"({qprof.param_bytes / prof.param_bytes:.0%} of f32)")
        profiles.append((quantize, qprof))
    chosen = None
    single_chip = {}
    for label, p in profiles:
        searcher = PlacementSearcher(p, inv, traffic)
        lines.append(f"--- {label} plan table ---")
        lines.append(plan_table(searcher.all_plans()))
        try:
            single_chip[label] = searcher.search(max_devices=1)
        except NoFeasiblePlacement:
            single_chip[label] = None
        try:
            best = searcher.search()
        except NoFeasiblePlacement as e:
            lines.append(f"{label}: NO FEASIBLE PLAN: {e}")
            continue
        lines.append(
            f"{label} chosen: dp={best.dp} tp={best.tp} "
            f"({best.devices} chips)  per-device HBM "
            f"{best.hbm_bytes_per_device / 2**30:.3f} GiB "
            f"({best.hbm_fraction:.0%})  comm "
            f"{best.collective_bytes_per_step / 2**20:.2f} MiB/step over "
            f"{best.collectives_per_dispatch} all-gathers  predicted "
            f"{best.predicted_qps:.1f} QPS "
            f"({best.predicted_qps_per_chip:.1f}/chip) at p95 "
            f"{best.predicted_p95_ms:.2f} ms")
        chosen = best  # with --quantize, the quantized plan is returned
    if quantize and single_chip.get("f32") is None \
            and single_chip.get(quantize) is not None:
        lines.append(
            f"HEADLINE: must-shard at f32 (no single-chip plan fits "
            f"{hbm_gb} GiB) but SINGLE-CHIP under {quantize} "
            f"(dp={single_chip[quantize].dp} tp={single_chip[quantize].tp}, "
            f"{single_chip[quantize].hbm_bytes_per_device / 2**30:.3f} "
            f"GiB/dev)")
    if train_chips:
        # the training table rides next to the serving one (ISSUE 15):
        # same export, same inventory class, the §24 searcher
        ttext, tplan = train_placement_report(
            prof, chips=train_chips, hbm_gb=hbm_gb,
            peak_tflops=peak_tflops, hbm_gbps=hbm_gbps,
            link_gbps=link_gbps, global_batch=train_batch,
            optimizer=train_optimizer)
        lines.append(ttext)
        if tplan is None:
            chosen = None  # train infeasibility is the exit signal too
    return "\n".join(lines), chosen


def cmd_placement(argv):
    import argparse

    ap = argparse.ArgumentParser(
        prog="paddle_cli.py placement",
        description="search (dp, tp) parallelism placements for an "
                    "exported inference dir under the §18 cost model")
    ap.add_argument("export_dir", help="io.save_inference_model output dir")
    ap.add_argument("--chips", type=int, default=8)
    ap.add_argument("--hbm-gb", type=float, default=16.0)
    ap.add_argument("--peak-tflops", type=float, default=197.0)
    ap.add_argument("--hbm-gbps", type=float, default=820.0)
    ap.add_argument("--link-gbps", type=float, default=45.0)
    ap.add_argument("--batch-mix", default="1:0.7,8:0.3",
                    metavar="ROWS:W,...", help="traffic batch-size mix")
    ap.add_argument("--p95-ms", type=float, default=None,
                    help="fixed p95 budget (plans over it are infeasible)")
    ap.add_argument("--seq-len", type=int, default=None)
    ap.add_argument("--decode-slots", type=int, default=0,
                    help="account a decode KV pool of this many slots")
    ap.add_argument("--quantize", choices=("int8", "bf16"), default=None,
                    help="also search the weight-only quantized byte "
                         "account side by side (int8 weights ~1/4 the "
                         "HBM; a must-shard model can become single-chip "
                         "— the headline row) and return ITS plan")
    ap.add_argument("--train", type=int, default=None, metavar="N_CHIPS",
                    help="also print the TRAINING (dp, tp, pp, accum, "
                         "zero_stage) candidate table for N chips — 3D "
                         "ZeRO per-device HBM + modeled step time with "
                         "per-axis comm and pipeline schedule "
                         "(docs §24/§27); nonzero exit when nothing fits")
    ap.add_argument("--train-batch", type=int, default=64,
                    help="global batch the train searcher splits")
    ap.add_argument("--train-optimizer", default="adam",
                    help="optimizer type for the ZeRO state multiplier")
    args = ap.parse_args(argv)
    report, chosen = placement_report(
        args.export_dir, chips=args.chips, hbm_gb=args.hbm_gb,
        peak_tflops=args.peak_tflops, hbm_gbps=args.hbm_gbps,
        link_gbps=args.link_gbps, batch_mix=args.batch_mix,
        p95_ms=args.p95_ms, seq_len=args.seq_len,
        decode_slots=args.decode_slots, quantize=args.quantize,
        train_chips=args.train, train_batch=args.train_batch,
        train_optimizer=args.train_optimizer)
    print(report)
    return 0 if chosen is not None else 1


# -- goodput / profiles (docs/design.md §23) --------------------------------


def goodput_report_text(path):
    """(text, exit_code) — the testable core of ``cmd_goodput``: render a
    profile artifact's breakdown, or a flight bundle's goodput provider
    snapshot (profile pair + diff)."""
    sys.path.insert(0, REPO)
    import json as _json

    from paddle_tpu.obs.profile import (ProfileError, format_diff,
                                        goodput_report, load_profile)

    try:
        p = load_profile(path)
        return goodput_report(p), 0
    except ProfileError as e:
        profile_err = e
    # not a profile — maybe a flight bundle carrying the goodput provider
    try:
        with open(path) as f:
            doc = _json.load(f)
    except (OSError, ValueError):
        return f"unreadable: {profile_err}", 2
    gp = (doc.get("providers") or {}).get("goodput") \
        if isinstance(doc, dict) else None
    if not isinstance(gp, dict):
        return (f"{path}: neither a profile ({profile_err}) nor a bundle "
                f"with a goodput provider", 2)
    lines = []
    for prof in gp.get("profiles") or []:
        lines.append(goodput_report(prof))
        lines.append("")
    if isinstance(gp.get("diff"), dict):
        lines.append(format_diff(gp["diff"]))
    return ("\n".join(lines) or "bundle goodput provider is empty"), 0


def cmd_goodput(argv):
    import argparse

    ap = argparse.ArgumentParser(
        prog="paddle_cli.py goodput",
        description="render the taxonomy breakdown of a profile artifact "
                    "(obs/profile.py) or a flight bundle's goodput "
                    "provider")
    ap.add_argument("path", help="profile JSON or postmortem bundle")
    args = ap.parse_args(argv)
    text, rc = goodput_report_text(args.path)
    print(text)
    return rc


def profile_diff_report(base_path, cur_path, tolerance=None):
    """(text, diff) — the testable core of ``cmd_profile_diff``: the
    differential attributor over two persisted profiles, owners ranked."""
    sys.path.insert(0, REPO)
    from paddle_tpu.obs.profile import (diff_profiles, format_diff,
                                        load_profile)

    diff = diff_profiles(load_profile(base_path), load_profile(cur_path),
                         tolerance=tolerance)
    return format_diff(diff), diff


def cmd_profile_diff(argv):
    import argparse

    ap = argparse.ArgumentParser(
        prog="paddle_cli.py profile-diff",
        description="diff two profile artifacts and name the categories "
                    "owning the delta (nonzero exit on a regression "
                    "beyond tolerance)")
    ap.add_argument("base", help="the earlier profile JSON")
    ap.add_argument("cur", help="the later profile JSON")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="wall-ratio regression tolerance (default: the "
                         "obs_profile_diff_tolerance flag)")
    args = ap.parse_args(argv)
    sys.path.insert(0, REPO)
    from paddle_tpu.obs.profile import ProfileError

    try:
        text, diff = profile_diff_report(args.base, args.cur,
                                         tolerance=args.tolerance)
    except ProfileError as e:
        print(f"typed refusal: {e}", file=sys.stderr)
        return 2
    print(text)
    return 1 if diff["regressed"] else 0


def cmd_metrics_doc(argv):
    import argparse

    ap = argparse.ArgumentParser(
        prog="paddle_cli.py metrics-doc",
        description="generate docs/metrics.md from the live registries "
                    "(+ a source scan for lazily-registered instruments)")
    ap.add_argument("--out", default=os.path.join(REPO, "docs",
                                                  "metrics.md"),
                    help="output path ('-' = stdout)")
    args = ap.parse_args(argv)
    sys.path.insert(0, REPO)
    from paddle_tpu.obs.metrics_doc import render_doc

    text = render_doc()
    if args.out == "-":
        print(text)
    else:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"metrics contract written to {args.out} "
              f"({sum(1 for l in text.splitlines() if l.startswith('| `'))} "
              f"instruments)")
    return 0


def main():
    if len(sys.argv) < 2 or sys.argv[1] in ("-h", "--help", "help"):
        print(__doc__)
        print("usage: paddle_cli.py {train|version|trace|fleet|placement|"
              "doctor|replay|tune|goodput|profile-diff|metrics-doc} "
              "[args...]")
        return 0
    sub = sys.argv[1]
    if sub == "version":
        cmd_version()
        return 0
    if sub == "train":
        cmd_train(sys.argv[2:])
        return 0  # unreachable (execv)
    if sub == "trace":
        return cmd_trace(sys.argv[2:])
    if sub == "fleet":
        return cmd_fleet(sys.argv[2:])
    if sub == "placement":
        return cmd_placement(sys.argv[2:])
    if sub == "doctor":
        return cmd_doctor(sys.argv[2:])
    if sub == "replay":
        return cmd_replay(sys.argv[2:])
    if sub == "tune":
        return cmd_tune(sys.argv[2:])
    if sub == "goodput":
        return cmd_goodput(sys.argv[2:])
    if sub == "profile-diff":
        return cmd_profile_diff(sys.argv[2:])
    if sub == "metrics-doc":
        return cmd_metrics_doc(sys.argv[2:])
    print(f"unknown subcommand {sub!r}; use "
          f"train|version|trace|fleet|placement|doctor|replay|tune|"
          f"goodput|profile-diff|metrics-doc")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())

"""`paddle`-style CLI (<- paddle/scripts/submit_local.sh.in: the `paddle`
wrapper exposing train/version subcommands around paddle_trainer).

Subcommands:
  train    — launch a local training run of a benchmark model
             (the paddle_trainer role; flags forward to the benchmark driver)
  version  — print framework/runtime versions
  trace    — summarize a Chrome-trace JSON (obs tracer / timeline.py
             output) without a browser: top spans by SELF time (child
             spans subtracted), per-stage duration histogram, slowest
             trace_ids. ``--convert OUT`` re-emits a normalized trace.
  fleet    — status table of serving replicas (health, queue, pipeline
             occupancy, MFU, weights version, derived circuit state)
             scraped from each endpoint's healthz + /metrics; endpoints
             as args or comma-separated. Unreachable replicas render as
             circuit=open.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
from collections import defaultdict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _probe_backends(timeout_s=45):
    """Platform list via a killable child: `version` is a host-side
    informational command, and an accelerator plugin probing absent
    hardware can hang jax backend init for minutes (the PR-1 benchmark
    driver hang) — that must bound-fail the backends line, not the CLI."""
    code = ("import jax; "
            "print(','.join(sorted({d.platform for d in jax.devices()})))")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, cwd=REPO,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return ["unavailable (backend probe timed out)"]
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return [f"unavailable ({tail[-1] if tail else r.returncode})"]
    return r.stdout.strip().split(",")


def cmd_version():
    sys.path.insert(0, REPO)
    import jax

    import paddle_tpu

    print("paddle_tpu (TPU-native Paddle-capability framework)")
    print("  jax:", jax.__version__)
    print("  backends:", ", ".join(_probe_backends()))
    from paddle_tpu.core.registry import registered_ops

    print("  ops registered:", len(registered_ops()))


def cmd_train(argv):
    driver = os.path.join(REPO, "benchmark", "fluid_benchmark.py")
    os.execv(sys.executable, [sys.executable, driver] + argv)


# -- trace inspection ------------------------------------------------------
_HIST_BUCKETS_MS = (0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                    1000, float("inf"))


def load_trace(path):
    """Chrome-trace JSON -> list of complete ('X') event dicts."""
    with open(path) as f:
        obj = json.load(f)
    events = obj.get("traceEvents", obj) if isinstance(obj, dict) else obj
    return [e for e in events if e.get("ph") == "X"]


def self_times(events):
    """name -> (count, total_us, self_us). Children are detected by strict
    time containment on the same (pid, tid) lane — works on any Chrome
    trace, not just ones carrying explicit parent links."""
    by_lane = defaultdict(list)
    for e in events:
        by_lane[(e.get("pid", 0), e.get("tid", 0))].append(e)
    agg = defaultdict(lambda: [0, 0.0, 0.0])  # count, total, self
    for lane in by_lane.values():
        lane.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
        stack = []  # (end_ts, event, child_total)
        def pop_until(ts):
            while stack and stack[-1][0] <= ts + 1e-9:
                end, ev, child = stack.pop()
                rec = agg[ev["name"]]
                rec[0] += 1
                rec[1] += ev.get("dur", 0.0)
                rec[2] += max(ev.get("dur", 0.0) - child, 0.0)
                if stack:
                    stack[-1][2] += ev.get("dur", 0.0)
        for e in lane:
            pop_until(e["ts"])
            stack.append([e["ts"] + e.get("dur", 0.0), e, 0.0])
        pop_until(float("inf"))
    return {n: tuple(v) for n, v in agg.items()}


def stage_histogram(events):
    """name -> per-_HIST_BUCKETS_MS counts of span durations."""
    hist = defaultdict(lambda: [0] * len(_HIST_BUCKETS_MS))
    for e in events:
        ms = e.get("dur", 0.0) / 1e3
        for i, b in enumerate(_HIST_BUCKETS_MS):
            if ms <= b:
                hist[e["name"]][i] += 1
                break
    return dict(hist)


def trace_report(events, top=15):
    """Human-readable summary (also what tests assert against)."""
    lines = []
    st = sorted(self_times(events).items(), key=lambda kv: -kv[1][2])
    lines.append(f"{'span':<38}{'calls':>7}{'total_ms':>12}{'self_ms':>12}")
    for name, (count, total, self_us) in st[:top]:
        lines.append(f"{name:<38}{count:>7}{total / 1e3:>12.3f}"
                     f"{self_us / 1e3:>12.3f}")
    hist = stage_histogram(events)
    lines.append("")
    lines.append("stage histogram (span count per duration bucket, ms):")
    labels = [("<=" + (f"{b:g}" if b != float("inf") else "inf"))
              for b in _HIST_BUCKETS_MS]
    for name in sorted(hist):
        nz = [(l, c) for l, c in zip(labels, hist[name]) if c]
        lines.append(f"  {name}: " + " ".join(f"{l}:{c}" for l, c in nz))
    slow = sorted((e for e in events
                   if e.get("args", {}).get("trace_id")),
                  key=lambda e: -e.get("dur", 0.0))
    if slow:
        lines.append("")
        lines.append("slowest traced requests:")
        for e in slow[:5]:
            lines.append(f"  {e['args']['trace_id']}  {e['name']}  "
                         f"{e.get('dur', 0.0) / 1e3:.3f}ms")
    return "\n".join(lines)


def cmd_trace(argv):
    import argparse

    ap = argparse.ArgumentParser(
        prog="paddle_cli.py trace",
        description="summarize/convert a Chrome-trace JSON")
    ap.add_argument("path", help="trace file (obs dump / timeline.py out)")
    ap.add_argument("--top", type=int, default=15,
                    help="rows in the self-time table")
    ap.add_argument("--convert", metavar="OUT",
                    help="also write a normalized pretty-printed trace")
    args = ap.parse_args(argv)
    events = load_trace(args.path)
    if not events:
        print(f"{args.path}: no complete ('X') trace events")
        return 1
    print(f"{args.path}: {len(events)} spans")
    print(trace_report(events, top=args.top))
    if args.convert:
        with open(args.convert, "w") as f:
            json.dump({"traceEvents": events}, f, indent=2)
        print(f"normalized trace written to {args.convert}")
    return 0


# -- fleet status ----------------------------------------------------------


def fleet_rows(endpoints, timeout=3.0):
    """Scrape each replica's healthz + metrics; one status dict per
    endpoint. The circuit column is DERIVED: an endpoint that cannot be
    scraped is what a router's breaker would hold open."""
    sys.path.insert(0, REPO)
    from paddle_tpu.serving import ServingClient
    from paddle_tpu.serving.fleet import scraped_gauges

    rows = []
    for ep in endpoints:
        row = {"endpoint": ep, "health": "unreachable", "circuit": "open",
               "queue": "-", "capacity": "-", "occupancy": "-", "mfu": "-",
               "weights": "-", "decode": ""}
        try:
            with ServingClient(ep, timeout=timeout) as c:
                hz = c.healthz()
                m = scraped_gauges(hz, c.metrics())
            row.update(
                health=hz.get("state", "?"), circuit="closed",
                queue=int(m["queue_depth"]),
                capacity=int(m["queue_capacity"]),
                occupancy=int(m["occupancy"]),
                mfu=m["mfu"],
                weights=int(m["weights_version"]))
            d = hz.get("decode")
            if d:
                row["decode"] = (f"{d['active_slots']}/{d['max_slots']} "
                                 f"slots")
        except Exception:
            pass
        rows.append(row)
    return rows


def fleet_report(rows):
    lines = [f"{'replica':<24}{'health':<12}{'circuit':<9}{'queue':>9}"
             f"{'occ':>5}{'mfu':>11}{'weights':>9}  decode"]
    for r in rows:
        q = (f"{r['queue']}/{r['capacity']}"
             if r["queue"] != "-" else "-")
        mfu = f"{r['mfu']:.2e}" if r["mfu"] != "-" else "-"
        lines.append(f"{r['endpoint']:<24}{r['health']:<12}"
                     f"{r['circuit']:<9}{q:>9}{str(r['occupancy']):>5}"
                     f"{mfu:>11}{str(r['weights']):>9}  {r['decode']}")
    healthy = sum(1 for r in rows if r["health"] == "healthy")
    lines.append(f"{healthy}/{len(rows)} replicas healthy")
    return "\n".join(lines)


def cmd_fleet(argv):
    import argparse

    ap = argparse.ArgumentParser(
        prog="paddle_cli.py fleet",
        description="status table of serving replicas from scraped "
                    "healthz + /metrics")
    ap.add_argument("endpoints", nargs="+",
                    help="replica endpoints (host:port, space- or "
                         "comma-separated)")
    ap.add_argument("--timeout", type=float, default=3.0,
                    help="per-replica scrape timeout (s)")
    args = ap.parse_args(argv)
    eps = [e for spec in args.endpoints for e in spec.split(",") if e]
    rows = fleet_rows(eps, timeout=args.timeout)
    print(fleet_report(rows))
    return 0 if all(r["health"] == "healthy" for r in rows) else 1


def main():
    if len(sys.argv) < 2 or sys.argv[1] in ("-h", "--help", "help"):
        print(__doc__)
        print("usage: paddle_cli.py {train|version|trace|fleet} [args...]")
        return 0
    sub = sys.argv[1]
    if sub == "version":
        cmd_version()
        return 0
    if sub == "train":
        cmd_train(sys.argv[2:])
        return 0  # unreachable (execv)
    if sub == "trace":
        return cmd_trace(sys.argv[2:])
    if sub == "fleet":
        return cmd_fleet(sys.argv[2:])
    print(f"unknown subcommand {sub!r}; use train|version|trace|fleet")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())

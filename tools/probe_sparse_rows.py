"""A/B: dense vs SelectedRows (is_sparse) embedding update on one chip.

Where does the device-side sparse optimizer pay? The dense path streams the
WHOLE table (scatter-add + optimizer pass ~7 passes over [V, E]); the sparse
path sorts/merges the batch's ids and gathers/scatters only touched rows.
Crossover is therefore set by table size vs batch rows.
Usage: python tools/probe_sparse_rows.py [V] [E] [batch] [slots]
"""
import json
import sys

sys.path.insert(0, ".")
import numpy as np  # noqa: E402

from bench import _slope_time  # noqa: E402


def run(V, E, B, S, is_sparse):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.param_attr import ParamAttr

    with fluid.unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data("ids", shape=[S], dtype="int64")
            y = fluid.layers.data("y", shape=[E], dtype="float32")
            emb = fluid.layers.embedding(
                ids, size=[V, E], is_sparse=is_sparse,
                param_attr=ParamAttr("tab"))
            pooled = fluid.layers.reduce_sum(emb, dim=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pooled, y))
            fluid.optimizer.Adam(1e-3).minimize(loss, startup)
    place = fluid.default_place()
    exe = fluid.Executor(place, amp=True)
    scope = fluid.Scope()
    exe.run(startup, scope=scope, seed=1)
    rng = np.random.RandomState(0)
    dev = place.jax_device()
    feed = {
        "ids": jax.device_put(rng.randint(0, V, (B, S)).astype("int32"), dev),
        "y": jax.device_put(rng.randn(B, E).astype("float32"), dev),
    }
    step, spread = _slope_time(
        lambda: exe.run(main, feed=feed, fetch_list=[], scope=scope),
        lambda: exe.run(main, feed=feed, fetch_list=[loss], scope=scope),
        warmup=3, iters=40)
    print(json.dumps({
        "V": V, "E": E, "batch_rows": B * S, "is_sparse": is_sparse,
        "step_ms": round(step * 1e3, 3),
        "spread_ms": round(spread * 1e3, 3)}), flush=True)


if __name__ == "__main__":
    V = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    E = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    B = int(sys.argv[3]) if len(sys.argv) > 3 else 1024
    S = int(sys.argv[4]) if len(sys.argv) > 4 else 16
    for is_sparse in (False, True):
        run(V, E, B, S, is_sparse)

"""Round-6 dW-orientation matmul A/B probe (the slope instrument for
ops/pallas_matmul.py).

Two levels, same discipline as tools/probe_tlm*.py:

* ``kernel`` — slope-timed ms/call + effective TF/s for XLA vs the two
  Pallas strategies on each audited dW shape (head dW [8192,1024]^T @
  [8192,32000], FFN up/down dW, projection dW, and the longcontext
  siblings). Chained windows with a scalar fetch close the dispatch chain
  — the r4 lesson that an unfetched output lets XLA DCE the kernel (the
  425%-"MFU" artifact) and that block_until_ready returns early through
  the tunnel.
* ``model`` — the AUTHORITATIVE instrument (docs/perf.md measurement
  note): the full bench transformer step, slope-timed, with the dW flag
  forced off / direct / transpose / auto. A kernel-level win that does
  not reproduce here is a de-fusion loss (the r3 conv lesson) and must
  not ship.

Usage:
  python tools/probe_dw_matmul.py kernel            # bench shapes
  python tools/probe_dw_matmul.py kernel 1024,32000,8192 ...
  python tools/probe_dw_matmul.py model [off direct transpose auto]
"""
import json
import sys

sys.path.insert(0, ".")
import numpy as np  # noqa: E402


def probe_kernel(shapes):
    from paddle_tpu.ops.pallas_matmul import measure_dw, plan_blocks

    for (m, n, k) in shapes:
        res = measure_dw(m, n, k)
        gflop = 2.0 * m * n * k / 1e9
        rec = {"shape": [m, n, k], "plan": plan_blocks(m, n, k)}
        for name, ms in res.items():
            rec[f"{name}_ms"] = round(ms, 3)
            rec[f"{name}_tfs"] = round(gflop / ms, 1)
        best = min(("direct", "transpose"), key=lambda s: res[s])
        rec["verdict"] = best if res[best] < res["xla"] else "xla"
        print(json.dumps(rec), flush=True)


def probe_model(modes):
    """Model-level step A/B: bench.build_transformer_lm under each dW flag
    mode. Fresh program per mode (routing is a trace-time choice)."""
    import bench
    from paddle_tpu import flags
    from paddle_tpu.ops import pallas_matmul

    # an explicit set_flag is always honored by bench's _maybe_tune_dw
    # (flags.is_set); 'auto' additionally drops any prior plan so the
    # builder's tuner measures afresh
    for mode in modes:
        flags.set_flag("pallas_dw_matmul", mode)
        if mode == "auto":
            pallas_matmul.reset()
        routes0 = pallas_matmul.route_count
        run_step, fetch = bench.build_transformer_lm(k=bench.PIPE_K)
        step, spread = bench._slope_time(run_step, fetch, warmup=3, iters=20,
                                         steps_per_call=bench.PIPE_K)
        tok_s = bench.TLM_BATCH * bench.TLM_T / step
        fpt = bench.lm_flops_per_token(bench.TLM_D, bench.TLM_LAYERS,
                                       bench.TLM_FF, bench.TLM_T,
                                       bench.TLM_VOCAB)
        print(json.dumps({
            "mode": mode,
            "routed_dots": pallas_matmul.route_count - routes0,
            "step_ms": round(step * 1e3, 2),
            "spread_ms": round(spread * 1e3, 2),
            "tok_s": round(tok_s, 1),
            "mfu": round(tok_s * fpt / 1e12 / bench.PEAK_TFLOPS, 4),
        }), flush=True)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "kernel"
    rest = sys.argv[2:]
    if which == "kernel":
        from paddle_tpu.ops.pallas_matmul import BENCH_DW_SHAPES, LC_DW_SHAPES

        shapes = ([tuple(int(x) for x in s.split(",")) for s in rest]
                  if rest else list(BENCH_DW_SHAPES) + list(LC_DW_SHAPES))
        probe_kernel(shapes)
    elif which == "model":
        probe_model(rest or ["off", "direct", "transpose", "auto"])
    else:
        raise SystemExit(f"unknown probe mode {which!r} (kernel|model)")

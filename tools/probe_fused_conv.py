"""On-chip probe: fused Pallas conv+BN kernels vs the XLA op chain at the
ResNet-50 bs128 layer shapes. Run from /root/repo on the real TPU:

    python tools/probe_fused_conv.py [--batch 128]

Timing is tunnel-proof: the unit under test is a TWO-LAYER cell
(normalize+relu -> conv -> stats, twice, the second layer consuming the
first's raw output and batch statistics — exactly the framework's
training-mode dataflow), iterated inside jax.lax.fori_loop with the cell
output feeding the next iteration (serialized, un-hoistable, un-DCE-able).
Per-cell time is the slope between two trip counts, so dispatch/RPC
constants cancel; the fetch is the tiny stats carry (a real host transfer —
the tunnel's block_until_ready returns early).
"""
import argparse
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from paddle_tpu.ops.pallas_conv import (bn_affine, fused_conv3x3_bn,
                                        fused_matmul_bn, moments_from_sums)


def affine_from_stats(st, count, gamma=1.1, beta=0.05):
    mean, var = moments_from_sums(st, count)
    return bn_affine(mean, var, jnp.full_like(mean, gamma),
                     jnp.full_like(mean, beta))


def xla_layer_mm(x, w, a, b):
    xf = jnp.maximum(x.astype(jnp.float32) * a + b, 0.0).astype(jnp.bfloat16)
    y = jax.lax.dot_general(xf, w, (((1,), (0,)), ((), ())))
    yf = y.astype(jnp.float32)
    return y, jnp.stack([jnp.sum(yf, 0), jnp.sum(yf * yf, 0)])


def pallas_layer_mm(x, w, a, b):
    return fused_matmul_bn(x, w, (a, b))


def xla_layer_c3(x, w, a, b):
    xf = jnp.maximum(x.astype(jnp.float32) * a + b, 0.0).astype(jnp.bfloat16)
    y = jax.lax.conv_general_dilated(
        xf, w, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    yf = y.astype(jnp.float32)
    return y, jnp.stack([jnp.sum(yf, (0, 1, 2)), jnp.sum(yf * yf, (0, 1, 2))])


def pallas_layer_c3(x, w, a, b):
    return fused_conv3x3_bn(x, w, (a, b))


def make_cell_loop(layer, w1, w2, count):
    """(x, a, b, n) -> stats carry after n chained two-layer cells."""

    def cell(x, a, b):
        y1, st1 = layer(x, w1, a, b)
        a1, b1 = affine_from_stats(st1, count)
        y2, st2 = layer(y1, w2, a1, b1)
        a2, b2 = affine_from_stats(st2, count)
        return y2, a2, b2, st2

    def run(x, a, b, n):
        def body(_, carry):
            x, a, b, _st = carry
            y2, a2, b2, st2 = cell(x, a, b)
            return (y2, a2, b2, st2)

        st0 = jnp.zeros((2, x.shape[-1] if x.ndim == 2 else w2.shape[-1]),
                        jnp.float32)
        out = jax.lax.fori_loop(0, n, body, (x, a, b, st0))
        return out[3]

    return jax.jit(run)


def slope_cell_ms(jfn, x, a, b, n1=10, n2=110, reps=3):
    np.asarray(jfn(x, a, b, 2))  # compile + warm

    def t(n):
        t0 = time.perf_counter()
        np.asarray(jfn(x, a, b, n))
        return time.perf_counter() - t0

    slopes = []
    for _ in range(reps):
        t1, t2 = t(n1), t(n2)
        slopes.append((t2 - t1) / (n2 - n1))
    return float(np.median(slopes)) * 1e3  # ms per cell (2 layers)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--only", choices=["mm", "c3"], default=None)
    args = ap.parse_args()
    B = args.batch
    dev = jax.devices()[0]
    rng = np.random.RandomState(0)

    print("== 1x1 conv cell: K->N->K (two fused matmuls) ==", flush=True)
    for hw, k, n in ([] if args.only == "c3" else
                     [(56, 256, 64), (28, 512, 128), (14, 1024, 256),
                      (7, 2048, 512)]):
        m = B * hw * hw
        x = jax.device_put(rng.randn(m, k).astype(np.float32) * 0.5,
                           dev).astype(jnp.bfloat16)
        w1 = jax.device_put(rng.randn(k, n).astype(np.float32) * 0.05,
                            dev).astype(jnp.bfloat16)
        w2 = jax.device_put(rng.randn(n, k).astype(np.float32) * 0.05,
                            dev).astype(jnp.bfloat16)
        a, b = bn_affine(jnp.zeros(k), jnp.ones(k), jnp.ones(k) * 1.1,
                         jnp.zeros(k) + 0.05)
        gf = 2 * 2 * m * k * n / 1e9  # two layers
        res = {}
        carries = {}
        for name, layer in [("xla", xla_layer_mm), ("pallas", pallas_layer_mm)]:
            jfn = make_cell_loop(layer, w1, w2, m)
            carries[name] = jfn(x, a, b, 1)
            res[name] = slope_cell_ms(jfn, x, a, b)
        c_x, c_p = carries["xla"], carries["pallas"]
        serr = float(jnp.max(jnp.abs(c_x - c_p) / (jnp.abs(c_x) + 1e3)))
        print(f"M={m:7d} K={k:4d} N={n:4d}: xla {res['xla']:7.3f} ms "
              f"({gf/res['xla']:6.1f} TF/s)  pallas {res['pallas']:7.3f} ms "
              f"({gf/res['pallas']:6.1f} TF/s)  serr {serr:.2e}", flush=True)

    print("== 3x3 conv cell (two fused 3x3 convs, K->K) ==", flush=True)
    for hw, k in ([] if args.only == "mm" else
                  [(56, 64), (28, 128), (14, 256), (7, 512)]):
        x = jax.device_put(
            rng.randn(B, hw, hw, k).astype(np.float32) * 0.5, dev
        ).astype(jnp.bfloat16)
        w1 = jax.device_put(
            rng.randn(3, 3, k, k).astype(np.float32) * 0.05, dev
        ).astype(jnp.bfloat16)
        w2 = jax.device_put(
            rng.randn(3, 3, k, k).astype(np.float32) * 0.05, dev
        ).astype(jnp.bfloat16)
        a, b = bn_affine(jnp.zeros(k), jnp.ones(k), jnp.ones(k) * 1.1,
                         jnp.zeros(k) + 0.05)
        count = B * hw * hw
        gf = 2 * 2 * B * hw * hw * 9 * k * k / 1e9
        res = {}
        carries = {}
        for name, layer in [("xla", xla_layer_c3), ("pallas", pallas_layer_c3)]:
            jfn = make_cell_loop(layer, w1, w2, count)
            carries[name] = jfn(x, a, b, 1)
            res[name] = slope_cell_ms(jfn, x, a, b)
        c_x, c_p = carries["xla"], carries["pallas"]
        serr = float(jnp.max(jnp.abs(c_x - c_p) / (jnp.abs(c_x) + 1e3)))
        print(f"HW={hw:3d} K={k:4d}: xla {res['xla']:7.3f} ms "
              f"({gf/res['xla']:6.1f} TF/s)  pallas {res['pallas']:7.3f} ms "
              f"({gf/res['pallas']:6.1f} TF/s)  serr {serr:.2e}", flush=True)


if __name__ == "__main__":
    main()

"""Long-context LM A/B: remat policy x fused-head chunk variants.

Usage: python tools/probe_lc.py "policy[,chunk=N][,noremat][,densehead]" ...
policy in {nothing, flash, dots_flash, dots}
"""
import json
import sys

sys.path.insert(0, ".")
import numpy as np  # noqa: E402

from bench import (LC_BATCH, LC_D, LC_LAYERS, LC_T, LC_VOCAB,  # noqa: E402
                   PEAK_TFLOPS, _slope_time)


def run(policy, chunk=4096, use_recompute=True, fused=True):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models.transformer import transformer_lm
    from paddle_tpu import layers as L

    orig = L.fused_linear_cross_entropy
    if chunk != 4096:
        def patched(x, size, label, param_attr=None, bias_attr=None,
                    chunk_=chunk, name=None, **kw):
            return orig(x, size, label, param_attr=param_attr,
                        bias_attr=bias_attr, chunk=chunk_, name=name)
        L.fused_linear_cross_entropy = patched
    try:
        with fluid.unique_name.guard():
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                ids = fluid.layers.data("ids", shape=[LC_T], dtype="int64")
                labels = fluid.layers.data("labels", shape=[LC_T],
                                           dtype="int64")
                _, loss = transformer_lm(
                    ids, labels, vocab_size=LC_VOCAB, max_len=LC_T,
                    d_model=LC_D, n_heads=8, n_layers=LC_LAYERS,
                    d_ff=4 * LC_D, use_recompute=use_recompute,
                    fused_head=fused, use_bias=False,
                    recompute_policy=(None if policy in (None, "nothing")
                                      else policy))
                fluid.optimizer.Adam(1e-4).minimize(loss, startup)
    finally:
        L.fused_linear_cross_entropy = orig
    place = fluid.default_place()
    exe = fluid.Executor(place, amp=True)
    scope = fluid.Scope()
    exe.run(startup, scope=scope, seed=17)
    rng = np.random.RandomState(0)
    dev = place.jax_device()
    X = jax.device_put(
        rng.randint(0, LC_VOCAB, (LC_BATCH, LC_T)).astype("int32"), dev)
    feed = {"ids": X, "labels": X}
    step, spread = _slope_time(
        lambda: exe.run(main, feed=feed, fetch_list=[], scope=scope),
        lambda: exe.run(main, feed=feed, fetch_list=[loss], scope=scope),
        warmup=2, iters=30)
    tok_s = LC_BATCH * LC_T / step
    n_params = (LC_LAYERS * (4 * LC_D * LC_D + 2 * LC_D * 4 * LC_D)
                + LC_VOCAB * LC_D)
    fpt = 6 * n_params + 6 * LC_LAYERS * LC_D * LC_T
    print(json.dumps({
        "policy": policy, "chunk": chunk, "remat": use_recompute,
        "fused_head": fused,
        "tok_s": round(tok_s, 1), "mfu": round(tok_s * fpt / 1e12
                                               / PEAK_TFLOPS, 4),
        "step_ms": round(step * 1e3, 2),
        "spread_ms": round(spread * 1e3, 2)}), flush=True)


if __name__ == "__main__":
    for spec in sys.argv[1:]:
        parts = spec.split(",")
        policy = parts[0]
        chunk = 4096
        use_recompute = True
        fused = True
        for p in parts[1:]:
            if p.startswith("chunk="):
                chunk = int(p[6:])
            elif p == "noremat":
                use_recompute = False
            elif p == "densehead":
                fused = False
        run(policy, chunk, use_recompute, fused)

"""Round-5 transformer A/B probe: batch / bias / attention-packing variants.

Model-level slope timing (the authoritative instrument, docs/perf.md).
Usage: python tools/probe_tlm_r5.py "B[,nobias][,hb=N][,fusedqkv]" ...
e.g. python tools/probe_tlm_r5.py 8 8,nobias 8,nobias,hb=2
"""
import json
import sys

sys.path.insert(0, ".")
import numpy as np  # noqa: E402

from bench import (PEAK_TFLOPS, TLM_D, TLM_FF, TLM_LAYERS, TLM_T,  # noqa: E402
                   TLM_VOCAB, _slope_time)


def run(batch, use_bias=True, hb=None, fused_qkv=False):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tmod

    layers = tmod.layers
    orig = layers.flash_attention
    if hb is not None:
        def fa(q, k, v, causal=False, scale=None, q_block=512, k_block=512,
               heads_per_block=None, name=None):
            return orig(q, k, v, causal=causal, scale=scale, q_block=q_block,
                        k_block=k_block, heads_per_block=hb, name=name)
        layers.flash_attention = fa
    try:
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            ids = fluid.layers.data("ids", shape=[TLM_T], dtype="int64")
            labels = fluid.layers.data("labels", shape=[TLM_T], dtype="int64")
            _, loss = tmod.transformer_lm(
                ids, labels, vocab_size=TLM_VOCAB, max_len=TLM_T,
                d_model=TLM_D, n_heads=8, n_layers=TLM_LAYERS,
                d_ff=TLM_FF, use_bias=use_bias, fused_qkv=fused_qkv)
            fluid.optimizer.Adam(learning_rate=1e-4).minimize(loss, startup)
    finally:
        layers.flash_attention = orig
    place = fluid.default_place()
    exe = fluid.Executor(place, amp=True)
    scope = fluid.Scope()
    exe.run(startup, scope=scope, seed=13)
    rng = np.random.RandomState(0)
    dev = place.jax_device()
    X = jax.device_put(
        rng.randint(0, TLM_VOCAB, (batch, TLM_T)).astype("int32"), dev)
    feed = {"ids": X, "labels": X}
    step_time, spread = _slope_time(
        lambda: exe.run(main_prog, feed=feed, fetch_list=[], scope=scope),
        lambda: exe.run(main_prog, feed=feed, fetch_list=[loss], scope=scope),
        warmup=2, iters=max(10, 160 // batch))
    tok_s = batch * TLM_T / step_time
    n_params = (TLM_LAYERS * (4 * TLM_D * TLM_D + 2 * TLM_D * TLM_FF)
                + TLM_VOCAB * TLM_D)
    flops_per_token = 6 * n_params + 6 * TLM_LAYERS * TLM_D * TLM_T
    mfu = tok_s * flops_per_token / 1e12 / PEAK_TFLOPS
    print(json.dumps({
        "batch": batch, "bias": use_bias, "hb": hb, "fused_qkv": fused_qkv,
        "tok_s": round(tok_s, 1), "mfu": round(mfu, 4),
        "step_ms": round(step_time * 1e3, 2),
        "spread_ms": round(spread * 1e3, 2)}), flush=True)


if __name__ == "__main__":
    for spec in sys.argv[1:]:
        parts = spec.split(",")
        batch = int(parts[0])
        use_bias = "nobias" not in parts[1:]
        hb = None
        fused_qkv = "fusedqkv" in parts[1:]
        for p in parts[1:]:
            if p.startswith("hb="):
                hb = int(p[3:])
        run(batch, use_bias, hb, fused_qkv)

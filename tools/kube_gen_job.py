"""Generate Kubernetes job manifests for multi-host training
(<- benchmark/fluid/kube_gen_job.py + kube_templates/).

The reference emitted pserver+trainer job pairs wired by PADDLE_* env vars;
on TPU the pserver plane is gone, so this emits one indexed Job per host
whose pods bootstrap jax.distributed through the SAME env protocol
paddle_tpu.distributed.init_distributed consumes:
PADDLE_TRAINER_ENDPOINTS / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ID.

Usage::

    python tools/kube_gen_job.py --name resnet --image myrepo/paddle-tpu \
        --hosts 4 --tpu v5e-16 \
        --cmd "python benchmark/fluid_benchmark.py --model resnet" > job.yaml
"""
from __future__ import annotations

import argparse


def gen_job(name: str, image: str, hosts: int, tpu: str, cmd: str,
            cpu: str = "8", memory: str = "32Gi", port: int = 8476) -> str:
    """Render one manifest per host, joined by '---' (plain text YAML —
    dependency-free, like the reference's template dicts)."""
    docs = []
    endpoints = ",".join(f"{name}-{i}.{name}:{port}" for i in range(hosts))
    for host_id in range(hosts):
        docs.append(f"""\
apiVersion: batch/v1
kind: Job
metadata:
  name: {name}-{host_id}
  labels:
    app: {name}
spec:
  backoffLimit: 0
  template:
    metadata:
      labels:
        app: {name}
        host-id: "{host_id}"
    spec:
      restartPolicy: Never
      hostname: {name}-{host_id}
      subdomain: {name}
      containers:
      - name: trainer
        image: {image}
        command: ["/bin/sh", "-c"]
        args: ["{cmd}"]
        env:
        - name: PADDLE_TRAINER_ENDPOINTS
          value: "{endpoints}"
        - name: PADDLE_TRAINERS_NUM
          value: "{hosts}"
        - name: PADDLE_TRAINER_ID
          value: "{host_id}"
        - name: JAX_PLATFORMS
          value: "tpu"
        ports:
        - containerPort: {port}
        resources:
          requests:
            cpu: "{cpu}"
            memory: {memory}
            google.com/tpu: "{tpu}"
          limits:
            google.com/tpu: "{tpu}"
""")
    svc = f"""\
apiVersion: v1
kind: Service
metadata:
  name: {name}
spec:
  clusterIP: None
  selector:
    app: {name}
  ports:
  - port: {port}
"""
    return "---\n".join(docs + [svc])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--name", required=True)
    p.add_argument("--image", required=True)
    p.add_argument("--hosts", type=int, default=1)
    p.add_argument("--tpu", default="v5e-8", help="TPU resource request")
    p.add_argument("--cmd", required=True)
    p.add_argument("--cpu", default="8")
    p.add_argument("--memory", default="32Gi")
    args = p.parse_args()
    print(gen_job(args.name, args.image, args.hosts, args.tpu, args.cmd,
                  cpu=args.cpu, memory=args.memory))


if __name__ == "__main__":
    main()

"""On-chip probe: flash-attention fwd+bwd rate vs heads_per_block packing.

The d_head<128 configs leave the MXU contraction half-filled and double the
sequential Pallas grid; packing 128//d heads per grid cell
(ops/pallas_attention.py::_heads_per_block) amortizes the per-cell loop/DMA
overhead. This probe measures the packed vs unpacked kernels at the
docs/perf.md microbench shape (B8 T1024 H16 D64) with slope timing and a
data-dependent chain that consumes ALL kernel outputs (dq+dk+dv feed the
next step — XLA would DCE an unused dkv kernel and fake the number).

Usage: python tools/probe_small_head.py B,T,H,D,hpb,qb,kb [...]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, ".")
from paddle_tpu.ops.pallas_attention import (flash_attention_bwd,
                                             flash_attention_fwd)

PEAK = 191e12  # measured bf16 matmul ceiling on this chip (docs/perf.md)


def bench(B, T, H, D, hpb, qb, kb, reps=5, n1=None, n2=None):
    dev = [d for d in jax.devices() if d.platform == "tpu"][0]
    rng = np.random.RandomState(0)
    try:
        q = jax.device_put(rng.randn(B, T, H, D).astype(np.float32),
                           dev).astype(jnp.bfloat16)
        k = jax.device_put(rng.randn(B, T, H, D).astype(np.float32),
                           dev).astype(jnp.bfloat16)
        v = jax.device_put(rng.randn(B, T, H, D).astype(np.float32),
                           dev).astype(jnp.bfloat16)
        c = jnp.bfloat16(1e-3)

        def step(qq):
            out, lse = flash_attention_fwd(
                qq, k, v, causal=True, q_block=qb, k_block=kb,
                interpret=False, return_lse=True, heads_per_block=hpb)
            dq, dk, dv = flash_attention_bwd(
                qq, k, v, out, lse, out, causal=True, q_block=qb,
                k_block=kb, interpret=False, heads_per_block=hpb)
            return (dq + dk + dv).astype(qq.dtype)

        def make(n):
            @jax.jit
            def run(qq):
                return lax.fori_loop(0, n,
                                     lambda i, x: step(x) * c + x, qq)
            return run

        step1 = make(1)
        from paddle_tpu.profiler import slope_time
        ts = []
        for _ in range(reps):
            ts.append(slope_time(
                lambda: step1(q),
                lambda: step1(q).block_until_ready(),
                warmup=3, iters=60, prime=True))
        ts.sort()
        dt = ts[len(ts) // 2]  # median: robust to tunnel-weather outliers
        flops = B * H * 7 * 2 * T * T * D * 0.5  # causal fwd+bwd matmuls
        print(f"B{B} T{T} H{H} D{D} hpb={hpb} qb={qb} kb={kb}: "
              f"{dt*1e3:.3f} ms  MFU {flops/dt/PEAK*100:.1f}%  "
              f"(spread {ts[-1]/ts[0]:.2f}x)", flush=True)
    except Exception as e:  # noqa: BLE001 - probe reports and continues
        print(f"B{B} T{T} H{H} D{D} hpb={hpb} qb={qb} kb={kb}: "
              f"FAIL {str(e)[:90]}", flush=True)


if __name__ == "__main__":
    specs = sys.argv[1:] or ["8,1024,16,64,1,512,512",
                             "8,1024,16,64,2,1024,512",
                             "8,1024,8,128,1,512,512"]
    for spec in specs:
        bench(*[int(x) for x in spec.split(",")])

"""Perf lab: hand-written pure-JAX ResNet-50 train step as a throughput
ceiling reference for bench.py, plus a step-pipeline sweep.

The framework's bench (bench.py) runs ResNet-50 through the Program->XLA
executor. This script runs the *same math* written directly in jax, so the
difference isolates framework-introduced overhead (op-boundary casts, BN
materialization, grad recomputation that XLA failed to CSE, ...) from
chip/XLA limits. Variants:

  python tools/perf_lab.py nchw      # framework's layout
  python tools/perf_lab.py nhwc      # TPU-preferred logical layout
  python tools/perf_lab.py pipeline  # sweep run_steps window k in {1,2,4}
                                     # and DevicePrefetcher depth in {1,2,4}
                                     # on a small framework workload and
                                     # report step_ms per config — one
                                     # command to spot a pipelining
                                     # regression (docs/design.md §13)
  python tools/perf_lab.py decode    # sweep the decode-serving knobs
                                     # (max_slots x KV bucket ladder x
                                     # prefill chunk) over a mixed-length
                                     # generation workload; prints tokens/s
                                     # per config and emits the CHOSEN
                                     # config as the final JSON line
                                     # (docs/design.md §16)

Prints images/sec and analytic MFU (12.3 GFLOP/img fwd+bwd on a
~197 TFLOP/s bf16 v5e chip) for the resnet modes; step_ms per knob for
``pipeline``.
"""
from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

BATCH = 128
IMAGE = 224
CLASSES = 1000
GFLOP_PER_IMG = 12.3
PEAK_TFLOPS = 197.0


def _conv(x, w, stride, layout):
    if layout == "nchw":
        dn = ("NCHW", "OIHW", "NCHW")
        pads = [(w.shape[2] // 2, w.shape[2] // 2)] * 2
    else:
        dn = ("NHWC", "HWIO", "NHWC")
        pads = [(w.shape[0] // 2, w.shape[0] // 2)] * 2
    return jax.lax.conv_general_dilated(
        x, w.astype(jnp.bfloat16), (stride, stride), pads,
        dimension_numbers=dn)


def _bn(x, p, layout, training=True):
    caxis = 1 if layout == "nchw" else 3
    axes = tuple(i for i in range(4) if i != caxis)
    shape = [1] * 4
    shape[caxis] = -1
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.var(xf, axis=axes)
    inv = jax.lax.rsqrt(var + 1e-5)
    y = (xf - mean.reshape(shape)) * inv.reshape(shape) * p["scale"].reshape(shape) \
        + p["bias"].reshape(shape)
    return y.astype(x.dtype)


def init_params(rng, layout):
    params = {}

    def conv_p(name, cin, cout, k):
        fan = cin * k * k
        w = rng.randn(cout, cin, k, k).astype(np.float32) * np.sqrt(2.0 / fan)
        if layout == "nhwc":
            w = w.transpose(2, 3, 1, 0)  # OIHW -> HWIO
        params[name + "_w"] = w
        params[name + "_bn"] = {
            "scale": np.ones(cout, np.float32),
            "bias": np.zeros(cout, np.float32),
        }
        return name

    blocks = []
    conv_p("stem", 3, 64, 7)
    cin = 64
    for stage, (cmid, n, stride) in enumerate(
            [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]):
        for i in range(n):
            name = f"s{stage}b{i}"
            s = stride if i == 0 else 1
            conv_p(name + "_c1", cin, cmid, 1)
            conv_p(name + "_c2", cmid, cmid, 3)
            conv_p(name + "_c3", cmid, cmid * 4, 1)
            if cin != cmid * 4 or s != 1:
                conv_p(name + "_sc", cin, cmid * 4, 1)
            blocks.append((name, s, cin != cmid * 4 or s != 1))
            cin = cmid * 4
    params["fc_w"] = (rng.randn(2048, CLASSES).astype(np.float32)
                     * np.sqrt(1.0 / 2048))
    params["fc_b"] = np.zeros(CLASSES, np.float32)
    return params, blocks


def forward(params, blocks, img, label, layout):
    x = img.astype(jnp.bfloat16)
    if layout == "nhwc":
        x = jnp.transpose(x, (0, 2, 3, 1))
    x = _bn(_conv(x, params["stem_w"], 2, layout), params["stem_bn"], layout)
    x = jax.nn.relu(x)
    wdims = (1, 2) if layout == "nhwc" else (2, 3)
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max,
        tuple(3 if i in wdims else 1 for i in range(4)),
        tuple(2 if i in wdims else 1 for i in range(4)),
        [(1, 1) if i in wdims else (0, 0) for i in range(4)])
    for name, stride, has_sc in blocks:
        short = x
        if has_sc:
            short = _bn(_conv(x, params[name + "_sc_w"], stride, layout),
                        params[name + "_sc_bn"], layout)
        y = jax.nn.relu(_bn(_conv(x, params[name + "_c1_w"], stride, layout),
                            params[name + "_c1_bn"], layout))
        y = jax.nn.relu(_bn(_conv(y, params[name + "_c2_w"], 1, layout),
                            params[name + "_c2_bn"], layout))
        y = _bn(_conv(y, params[name + "_c3_w"], 1, layout),
                params[name + "_c3_bn"], layout)
        x = jax.nn.relu(short + y)
    x = jnp.mean(x.astype(jnp.float32), axis=wdims)  # [N, 2048]
    logits = x.astype(jnp.bfloat16) @ params["fc_w"].astype(jnp.bfloat16)
    logits = logits.astype(jnp.float32) + params["fc_b"]
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, label, axis=1))


def pipeline_mode(steps: int = 64):
    """Sweep the step-pipeline knobs on a small framework MLP workload.

    Three rows per knob value k in {1, 2, 4}:

    * ``run_steps k=N``    — fused scan window over device-resident feeds
      (the bench.py hot path; k=1 is the unfused per-step dispatch)
    * ``prefetch depth=N`` — host-fed reader behind a DevicePrefetcher
      (H2D overlap; depth=1 still overlaps conversion, just single-buffered)

    step_ms should be monotonically non-increasing in k on a host-bound
    workload; a regression here means the pipeline stopped overlapping.
    """
    import os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import paddle_tpu as fluid

    def build():
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.unique_name.guard():
            with fluid.program_guard(main_prog, startup):
                x = fluid.layers.data("x", shape=[256], dtype="float32")
                y = fluid.layers.data("y", shape=[1], dtype="float32")
                h = fluid.layers.fc(x, size=512, act="relu")
                h = fluid.layers.fc(h, size=512, act="relu")
                pred = fluid.layers.fc(h, size=1)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                fluid.optimizer.Adam(learning_rate=1e-3).minimize(
                    loss, startup)
        exe = fluid.Executor(fluid.default_place())
        scope = fluid.Scope()
        exe.run(startup, scope=scope, seed=5)
        return exe, main_prog, scope, loss

    rng = np.random.RandomState(0)
    X = rng.randn(steps, 128, 256).astype("float32")
    Y = rng.randn(steps, 128, 1).astype("float32")

    def timed(label, fn, nsteps):
        fn()  # warm (compile)
        t0 = time.perf_counter()
        fn()
        dt = (time.perf_counter() - t0) / nsteps
        print(f"{label:<24} step {dt * 1e3:8.3f} ms")
        return dt

    print(f"pipeline sweep: {steps} steps/config, MLP 256->512->512->1 "
          f"batch 128")
    for k in (1, 2, 4):
        exe, prog, scope, loss = build()
        feeds = [{"x": X[i], "y": Y[i]} for i in range(steps)]

        def run_fused(k=k, exe=exe, prog=prog, scope=scope):
            for i in range(0, steps, k):
                if k == 1:
                    exe.run(prog, feed=feeds[i], fetch_list=[], scope=scope)
                else:
                    exe.run_steps(prog, feed=feeds[i:i + k], fetch_list=[],
                                  scope=scope)
            jax.block_until_ready(scope.get(next(
                n for n in scope.var_names())))

        timed(f"run_steps k={k}", run_fused, steps)
    for depth in (1, 2, 4):
        exe, prog, scope, loss = build()

        def reader():
            for i in range(steps):
                yield {"x": X[i], "y": Y[i]}

        from paddle_tpu.reader import DevicePrefetcher
        pf = DevicePrefetcher(lambda: reader(), depth=depth, program=prog)

        def run_prefetched(pf=pf, exe=exe, prog=prog, scope=scope):
            for feed in pf():
                exe.run(prog, feed=feed, fetch_list=[], scope=scope)
            jax.block_until_ready(scope.get(next(
                n for n in scope.var_names())))

        timed(f"prefetch depth={depth}", run_prefetched, steps)


def decode_mode(n_requests: int = 32, seed: int = 7):
    """Sweep the decode-serving knobs (docs/design.md §16) over one fixed
    mixed-length generation workload and emit the winner as JSON.

    Grid: ``max_slots`` (batch width of the fixed-shape step — occupancy
    vs per-step cost), KV bucket ladder (``fine`` = every power of two:
    tight attention windows, more compiled signatures; ``coarse`` = every
    other rung: half the signatures, wider windows), ``prefill_chunk``
    (0 = whole-prompt buckets; N = fixed N-token chunks, bounding the
    stall a long prompt inflicts on in-flight lanes). Each config is run
    once to warm its executables (this backend's first ~30 calls per
    signature run slow) and once measured.
    """
    import json
    import os
    import tempfile

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import paddle_tpu as fluid
    from paddle_tpu import io
    from paddle_tpu.models.transformer import transformer_lm
    from paddle_tpu.serving.decode import DecodeEngine, GenerationBatcher
    from paddle_tpu.serving.engine import pow2_ladder

    V, T, D, H, L, FF = 512, 128, 64, 4, 2, 128
    d = os.path.join(tempfile.mkdtemp(prefix="perf_lab_decode_"), "lm")
    with fluid.unique_name.guard():
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            ids = fluid.layers.data("ids", shape=[T], dtype="int64")
            labels = fluid.layers.data("labels", shape=[T], dtype="int64")
            logits, _loss = transformer_lm(
                ids, labels, vocab_size=V, max_len=T, d_model=D, n_heads=H,
                n_layers=L, d_ff=FF)
        exe = fluid.Executor(fluid.default_place())
        scope = fluid.Scope()
        exe.run(startup, scope=scope, seed=seed)
        io.save_inference_model(d, ["ids"], [logits], exe, main_prog,
                                scope=scope)

    rng = np.random.RandomState(seed)
    prompts = [rng.randint(0, V, size=(int(rng.randint(4, 48)),))
               for _ in range(n_requests)]
    # bimodal budgets: the chat-shaped mix where continuous batching's
    # retire-and-admit discipline matters most
    budgets = [int(b) for b in np.where(rng.rand(n_requests) < 0.7,
                                        rng.randint(4, 16, n_requests),
                                        rng.randint(48, 72, n_requests))]
    total_budget = sum(budgets)
    print(f"decode sweep: {n_requests} generations, prompts 4-47 tokens, "
          f"budgets {min(budgets)}-{max(budgets)} "
          f"(sum {total_budget}), LM V={V} T={T} D={D} L={L}")

    full = tuple(b for b in pow2_ladder(T) if b >= 16)
    ladders = {"fine": full, "coarse": full[1::2] + (
        () if full[-1] in full[1::2] else (full[-1],))}
    rows = []
    for slots in (4, 8, 16):
        for lname, ladder in ladders.items():
            for chunk in (0, 16):
                eng = DecodeEngine(d, max_slots=slots, kv_buckets=ladder,
                                   prefill_chunk=chunk)
                eng.warmup()

                def run_once(eng=eng, slots=slots):
                    gb = GenerationBatcher(eng, queue_capacity=n_requests,
                                           default_max_new_tokens=64)
                    try:
                        t0 = time.monotonic()
                        futs = [gb.submit(p, max_new_tokens=b)
                                for p, b in zip(prompts, budgets)]
                        toks = sum(len(f.result(timeout=600).tokens)
                                   for f in futs)
                        return toks, time.monotonic() - t0
                    finally:
                        gb.close()

                run_once()  # warm the executables
                toks, dt = run_once()
                rate = toks / dt
                rows.append({"max_slots": slots, "kv_buckets": lname,
                             "ladder": list(ladder), "prefill_chunk": chunk,
                             "tokens": toks, "seconds": round(dt, 3),
                             "tokens_per_s": round(rate, 1),
                             "signatures": eng.cache_info()["size"]})
                print(f"slots={slots:<3} buckets={lname:<7} "
                      f"chunk={chunk:<3} {rate:8.1f} tok/s  "
                      f"({toks} tokens in {dt:.2f}s, "
                      f"{rows[-1]['signatures']} signatures)")
    best = max(rows, key=lambda r: r["tokens_per_s"])
    print("chosen config:")
    print(json.dumps({"chosen": {k: best[k] for k in
                                 ("max_slots", "kv_buckets", "ladder",
                                  "prefill_chunk")},
                      "tokens_per_s": best["tokens_per_s"],
                      "rows": rows}))


def main():
    layout = sys.argv[1] if len(sys.argv) > 1 else "nchw"
    if layout == "pipeline":
        pipeline_mode()
        return
    if layout == "decode":
        decode_mode()
        return
    rng = np.random.RandomState(0)
    params, blocks = init_params(rng, layout)
    dev = jax.devices()[0]
    params = jax.device_put(params, dev)
    img = jax.device_put(rng.randn(BATCH, 3, IMAGE, IMAGE).astype(np.float32), dev)
    label = jax.device_put(rng.randint(0, CLASSES, (BATCH, 1)), dev)
    velo = jax.tree.map(jnp.zeros_like, params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, velo, img, label):
        loss, grads = jax.value_and_grad(
            lambda p: forward(p, blocks, img, label, layout))(params)
        velo = jax.tree.map(lambda v, g: 0.9 * v + g, velo, grads)
        params = jax.tree.map(lambda p, v: p - 0.1 * v, params, velo)
        return params, velo, loss

    for _ in range(5):
        params, velo, loss = step(params, velo, img, label)
    float(loss)

    def run_n(n):
        nonlocal params, velo
        t0 = time.perf_counter()
        for _ in range(n):
            params, velo, loss = step(params, velo, img, label)
        float(loss)
        return time.perf_counter() - t0

    t1, t2 = run_n(10), run_n(50)
    dt = (t2 - t1) / 40
    img_s = BATCH / dt
    mfu = img_s * GFLOP_PER_IMG / 1e3 / PEAK_TFLOPS
    print(f"pure-jax resnet50 {layout}: {img_s:.1f} img/s  "
          f"step {dt*1e3:.2f} ms  MFU {mfu*100:.1f}%")


if __name__ == "__main__":
    main()

"""Perf lab: hand-written pure-JAX ResNet-50 train step as a throughput
ceiling reference for bench.py, plus a step-pipeline sweep.

The framework's bench (bench.py) runs ResNet-50 through the Program->XLA
executor. This script runs the *same math* written directly in jax, so the
difference isolates framework-introduced overhead (op-boundary casts, BN
materialization, grad recomputation that XLA failed to CSE, ...) from
chip/XLA limits. Variants:

  python tools/perf_lab.py nchw      # framework's layout
  python tools/perf_lab.py nhwc      # TPU-preferred logical layout
  python tools/perf_lab.py pipeline  # sweep run_steps window k in {1,2,4}
                                     # and DevicePrefetcher depth in {1,2,4}
                                     # on a small framework workload and
                                     # report step_ms per config — one
                                     # command to spot a pipelining
                                     # regression (docs/design.md §13)
  python tools/perf_lab.py decode    # sweep the decode-serving knobs
                                     # (max_slots x KV bucket ladder x
                                     # prefill chunk) over a mixed-length
                                     # generation workload; prints tokens/s
                                     # per config and emits the CHOSEN
                                     # config as the final JSON line
                                     # (docs/design.md §16)
  python tools/perf_lab.py placement # run the parallelism placement
                                     # searcher (serving/placement.py) over
                                     # a grid of model sizes x chip counts
                                     # x traffic mixes; prints the chosen
                                     # plan per cell, then predicted-vs-
                                     # measured step time for a real tiny
                                     # model on the host CPU mesh; winner
                                     # as final JSON line (docs §18)
  python tools/perf_lab.py cpu [DIR] # CPU serving tuning sweep: threads x
                                     # weight-only quant mode (f32/int8/
                                     # bf16) x bucket ladder, each cell a
                                     # fresh subprocess (thread flags are
                                     # pre-jax-init only); writes the
                                     # export's cpu_tuned.json ONLY on a
                                     # >5% closed-loop win with the
                                     # agreement floor held (docs §20) —
                                     # ServingServer(quantize="auto")
                                     # adopts it
  python tools/perf_lab.py tune [DB] # the offline kernel-tuning sweep
                                     # (docs §21): dW strategies x ranked
                                     # block plans + the flash-attention
                                     # schedule surface, slope-timed
                                     # on-chip; adoptions land in the
                                     # persistent TuningDB only on >5%
                                     # measured wins, every negative is
                                     # recorded (the generated ledger).
                                     # Non-TPU backends print the search
                                     # space and record NOTHING

Prints images/sec and analytic MFU (12.3 GFLOP/img fwd+bwd on a
~197 TFLOP/s bf16 v5e chip) for the resnet modes; step_ms per knob for
``pipeline``.
"""
from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

BATCH = 128
IMAGE = 224
CLASSES = 1000
GFLOP_PER_IMG = 12.3
PEAK_TFLOPS = 197.0


def _conv(x, w, stride, layout):
    if layout == "nchw":
        dn = ("NCHW", "OIHW", "NCHW")
        pads = [(w.shape[2] // 2, w.shape[2] // 2)] * 2
    else:
        dn = ("NHWC", "HWIO", "NHWC")
        pads = [(w.shape[0] // 2, w.shape[0] // 2)] * 2
    return jax.lax.conv_general_dilated(
        x, w.astype(jnp.bfloat16), (stride, stride), pads,
        dimension_numbers=dn)


def _bn(x, p, layout, training=True):
    caxis = 1 if layout == "nchw" else 3
    axes = tuple(i for i in range(4) if i != caxis)
    shape = [1] * 4
    shape[caxis] = -1
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.var(xf, axis=axes)
    inv = jax.lax.rsqrt(var + 1e-5)
    y = (xf - mean.reshape(shape)) * inv.reshape(shape) * p["scale"].reshape(shape) \
        + p["bias"].reshape(shape)
    return y.astype(x.dtype)


def init_params(rng, layout):
    params = {}

    def conv_p(name, cin, cout, k):
        fan = cin * k * k
        w = rng.randn(cout, cin, k, k).astype(np.float32) * np.sqrt(2.0 / fan)
        if layout == "nhwc":
            w = w.transpose(2, 3, 1, 0)  # OIHW -> HWIO
        params[name + "_w"] = w
        params[name + "_bn"] = {
            "scale": np.ones(cout, np.float32),
            "bias": np.zeros(cout, np.float32),
        }
        return name

    blocks = []
    conv_p("stem", 3, 64, 7)
    cin = 64
    for stage, (cmid, n, stride) in enumerate(
            [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]):
        for i in range(n):
            name = f"s{stage}b{i}"
            s = stride if i == 0 else 1
            conv_p(name + "_c1", cin, cmid, 1)
            conv_p(name + "_c2", cmid, cmid, 3)
            conv_p(name + "_c3", cmid, cmid * 4, 1)
            if cin != cmid * 4 or s != 1:
                conv_p(name + "_sc", cin, cmid * 4, 1)
            blocks.append((name, s, cin != cmid * 4 or s != 1))
            cin = cmid * 4
    params["fc_w"] = (rng.randn(2048, CLASSES).astype(np.float32)
                     * np.sqrt(1.0 / 2048))
    params["fc_b"] = np.zeros(CLASSES, np.float32)
    return params, blocks


def forward(params, blocks, img, label, layout):
    x = img.astype(jnp.bfloat16)
    if layout == "nhwc":
        x = jnp.transpose(x, (0, 2, 3, 1))
    x = _bn(_conv(x, params["stem_w"], 2, layout), params["stem_bn"], layout)
    x = jax.nn.relu(x)
    wdims = (1, 2) if layout == "nhwc" else (2, 3)
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max,
        tuple(3 if i in wdims else 1 for i in range(4)),
        tuple(2 if i in wdims else 1 for i in range(4)),
        [(1, 1) if i in wdims else (0, 0) for i in range(4)])
    for name, stride, has_sc in blocks:
        short = x
        if has_sc:
            short = _bn(_conv(x, params[name + "_sc_w"], stride, layout),
                        params[name + "_sc_bn"], layout)
        y = jax.nn.relu(_bn(_conv(x, params[name + "_c1_w"], stride, layout),
                            params[name + "_c1_bn"], layout))
        y = jax.nn.relu(_bn(_conv(y, params[name + "_c2_w"], 1, layout),
                            params[name + "_c2_bn"], layout))
        y = _bn(_conv(y, params[name + "_c3_w"], 1, layout),
                params[name + "_c3_bn"], layout)
        x = jax.nn.relu(short + y)
    x = jnp.mean(x.astype(jnp.float32), axis=wdims)  # [N, 2048]
    logits = x.astype(jnp.bfloat16) @ params["fc_w"].astype(jnp.bfloat16)
    logits = logits.astype(jnp.float32) + params["fc_b"]
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, label, axis=1))


def pipeline_mode(steps: int = 64):
    """Sweep the step-pipeline knobs on a small framework MLP workload.

    Three rows per knob value k in {1, 2, 4}:

    * ``run_steps k=N``    — fused scan window over device-resident feeds
      (the bench.py hot path; k=1 is the unfused per-step dispatch)
    * ``prefetch depth=N`` — host-fed reader behind a DevicePrefetcher
      (H2D overlap; depth=1 still overlaps conversion, just single-buffered)

    step_ms should be monotonically non-increasing in k on a host-bound
    workload; a regression here means the pipeline stopped overlapping.
    """
    import os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import paddle_tpu as fluid

    def build():
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.unique_name.guard():
            with fluid.program_guard(main_prog, startup):
                x = fluid.layers.data("x", shape=[256], dtype="float32")
                y = fluid.layers.data("y", shape=[1], dtype="float32")
                h = fluid.layers.fc(x, size=512, act="relu")
                h = fluid.layers.fc(h, size=512, act="relu")
                pred = fluid.layers.fc(h, size=1)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                fluid.optimizer.Adam(learning_rate=1e-3).minimize(
                    loss, startup)
        exe = fluid.Executor(fluid.default_place())
        scope = fluid.Scope()
        exe.run(startup, scope=scope, seed=5)
        return exe, main_prog, scope, loss

    rng = np.random.RandomState(0)
    X = rng.randn(steps, 128, 256).astype("float32")
    Y = rng.randn(steps, 128, 1).astype("float32")

    def timed(label, fn, nsteps):
        fn()  # warm (compile)
        t0 = time.perf_counter()
        fn()
        dt = (time.perf_counter() - t0) / nsteps
        print(f"{label:<24} step {dt * 1e3:8.3f} ms")
        return dt

    print(f"pipeline sweep: {steps} steps/config, MLP 256->512->512->1 "
          f"batch 128")
    for k in (1, 2, 4):
        exe, prog, scope, loss = build()
        feeds = [{"x": X[i], "y": Y[i]} for i in range(steps)]

        def run_fused(k=k, exe=exe, prog=prog, scope=scope):
            for i in range(0, steps, k):
                if k == 1:
                    exe.run(prog, feed=feeds[i], fetch_list=[], scope=scope)
                else:
                    exe.run_steps(prog, feed=feeds[i:i + k], fetch_list=[],
                                  scope=scope)
            jax.block_until_ready(scope.get(next(
                n for n in scope.var_names())))

        timed(f"run_steps k={k}", run_fused, steps)
    for depth in (1, 2, 4):
        exe, prog, scope, loss = build()

        def reader():
            for i in range(steps):
                yield {"x": X[i], "y": Y[i]}

        from paddle_tpu.reader import DevicePrefetcher
        pf = DevicePrefetcher(lambda: reader(), depth=depth, program=prog)

        def run_prefetched(pf=pf, exe=exe, prog=prog, scope=scope):
            for feed in pf():
                exe.run(prog, feed=feed, fetch_list=[], scope=scope)
            jax.block_until_ready(scope.get(next(
                n for n in scope.var_names())))

        timed(f"prefetch depth={depth}", run_prefetched, steps)


def decode_mode(n_requests: int = 32, seed: int = 7):
    """Sweep the decode-serving knobs (docs/design.md §16) over one fixed
    mixed-length generation workload and emit the winner as JSON.

    Grid: ``max_slots`` (batch width of the fixed-shape step — occupancy
    vs per-step cost), KV bucket ladder (``fine`` = every power of two:
    tight attention windows, more compiled signatures; ``coarse`` = every
    other rung: half the signatures, wider windows), ``prefill_chunk``
    (0 = whole-prompt buckets; N = fixed N-token chunks, bounding the
    stall a long prompt inflicts on in-flight lanes). Each config is run
    once to warm its executables (this backend's first ~30 calls per
    signature run slow) and once measured.
    """
    import json
    import os
    import tempfile

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import paddle_tpu as fluid
    from paddle_tpu import io
    from paddle_tpu.models.transformer import transformer_lm
    from paddle_tpu.serving.decode import DecodeEngine, GenerationBatcher
    from paddle_tpu.serving.engine import pow2_ladder

    V, T, D, H, L, FF = 512, 128, 64, 4, 2, 128
    d = os.path.join(tempfile.mkdtemp(prefix="perf_lab_decode_"), "lm")
    with fluid.unique_name.guard():
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            ids = fluid.layers.data("ids", shape=[T], dtype="int64")
            labels = fluid.layers.data("labels", shape=[T], dtype="int64")
            logits, _loss = transformer_lm(
                ids, labels, vocab_size=V, max_len=T, d_model=D, n_heads=H,
                n_layers=L, d_ff=FF)
        exe = fluid.Executor(fluid.default_place())
        scope = fluid.Scope()
        exe.run(startup, scope=scope, seed=seed)
        io.save_inference_model(d, ["ids"], [logits], exe, main_prog,
                                scope=scope)

    rng = np.random.RandomState(seed)
    prompts = [rng.randint(0, V, size=(int(rng.randint(4, 48)),))
               for _ in range(n_requests)]
    # bimodal budgets: the chat-shaped mix where continuous batching's
    # retire-and-admit discipline matters most
    budgets = [int(b) for b in np.where(rng.rand(n_requests) < 0.7,
                                        rng.randint(4, 16, n_requests),
                                        rng.randint(48, 72, n_requests))]
    total_budget = sum(budgets)
    print(f"decode sweep: {n_requests} generations, prompts 4-47 tokens, "
          f"budgets {min(budgets)}-{max(budgets)} "
          f"(sum {total_budget}), LM V={V} T={T} D={D} L={L}")

    full = tuple(b for b in pow2_ladder(T) if b >= 16)
    ladders = {"fine": full, "coarse": full[1::2] + (
        () if full[-1] in full[1::2] else (full[-1],))}
    rows = []
    for slots in (4, 8, 16):
        for lname, ladder in ladders.items():
            for chunk in (0, 16):
                eng = DecodeEngine(d, max_slots=slots, kv_buckets=ladder,
                                   prefill_chunk=chunk)
                eng.warmup()

                def run_once(eng=eng, slots=slots):
                    gb = GenerationBatcher(eng, queue_capacity=n_requests,
                                           default_max_new_tokens=64)
                    try:
                        t0 = time.monotonic()
                        futs = [gb.submit(p, max_new_tokens=b)
                                for p, b in zip(prompts, budgets)]
                        toks = sum(len(f.result(timeout=600).tokens)
                                   for f in futs)
                        return toks, time.monotonic() - t0
                    finally:
                        gb.close()

                run_once()  # warm the executables
                toks, dt = run_once()
                rate = toks / dt
                rows.append({"max_slots": slots, "kv_buckets": lname,
                             "ladder": list(ladder), "prefill_chunk": chunk,
                             "tokens": toks, "seconds": round(dt, 3),
                             "tokens_per_s": round(rate, 1),
                             "signatures": eng.cache_info()["size"]})
                print(f"slots={slots:<3} buckets={lname:<7} "
                      f"chunk={chunk:<3} {rate:8.1f} tok/s  "
                      f"({toks} tokens in {dt:.2f}s, "
                      f"{rows[-1]['signatures']} signatures)")
    best = max(rows, key=lambda r: r["tokens_per_s"])
    print("chosen config:")
    print(json.dumps({"chosen": {k: best[k] for k in
                                 ("max_slots", "kv_buckets", "ladder",
                                  "prefill_chunk")},
                      "tokens_per_s": best["tokens_per_s"],
                      "rows": rows}))


def kv_mode(n_requests: int = 32, seed: int = 9):
    """Paged-KV sweep (docs/design.md §22): page size x pool pages x
    eviction watermark over a bimodal prefix mix, winner as the final
    JSON line (the PR-4 adoption discipline: record, don't hand-tune).

    The mix is bimodal the way real prefix traffic is: ~70% of requests
    share one of K hot templates (zipf-popular — these want big hits and
    cheap suffix prefill), ~30% are cold unique prompts (these want the
    pool to not be hogged by cached pages — the eviction watermark's
    job). Each config runs once warm-up (executables) and once measured;
    the score is measured tokens/s with the hit-token ratio and pool
    pressure recorded alongside, and exhaustion sheds counted (a config
    that sheds is reported, not hidden)."""
    import json
    import os
    import tempfile

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import paddle_tpu as fluid
    from paddle_tpu import io
    from paddle_tpu.models.transformer import transformer_lm
    from paddle_tpu.serving.decode import GenerationBatcher
    from paddle_tpu.serving.errors import QueueFullError
    from paddle_tpu.serving.kvcache import PagedDecodeEngine

    V, T, D, H, L, FF = 512, 128, 64, 4, 2, 128
    SLOTS = 8
    d = os.path.join(tempfile.mkdtemp(prefix="perf_lab_kv_"), "lm")
    with fluid.unique_name.guard():
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            ids = fluid.layers.data("ids", shape=[T], dtype="int64")
            labels = fluid.layers.data("labels", shape=[T], dtype="int64")
            logits, _loss = transformer_lm(
                ids, labels, vocab_size=V, max_len=T, d_model=D, n_heads=H,
                n_layers=L, d_ff=FF)
        exe = fluid.Executor(fluid.default_place())
        scope = fluid.Scope()
        exe.run(startup, scope=scope, seed=seed)
        io.save_inference_model(d, ["ids"], [logits], exe, main_prog,
                                scope=scope)

    rng = np.random.RandomState(seed)
    templates = [rng.randint(0, V, size=(40,)) for _ in range(3)]
    zipf = np.array([1.0, 0.5, 0.33])
    zipf /= zipf.sum()
    reqs = []
    for _ in range(n_requests):
        if rng.rand() < 0.7:  # hot: shared template + short suffix
            t = int(rng.choice(3, p=zipf))
            prompt = np.concatenate([
                templates[t],
                rng.randint(0, V, size=(int(rng.randint(2, 8)),))])
        else:  # cold: unique prompt, no reuse possible
            prompt = rng.randint(0, V, size=(int(rng.randint(8, 48)),))
        reqs.append((prompt, int(rng.randint(8, 24))))
    print(f"kv sweep: {n_requests} generations (70% over 3 zipf "
          f"templates x 40 tokens, 30% cold), LM V={V} T={T} D={D} L={L}, "
          f"{SLOTS} slots")

    rows = []
    for page_len in (8, 16):
        for pool_frac, pool_label in ((1.0, "dense-equiv"),
                                      (0.5, "overcommit2"),
                                      (0.25, "overcommit4")):
            for watermark in (0.0, 0.25):
                pool_pages = max(int(SLOTS * (T // page_len) * pool_frac),
                                 T // page_len)
                eng = PagedDecodeEngine(
                    d, max_slots=SLOTS, page_len=page_len,
                    pool_pages=pool_pages, evict_watermark=watermark)
                eng.warmup()

                def run_once(eng=eng):
                    gb = GenerationBatcher(eng, queue_capacity=n_requests)
                    shed = 0
                    try:
                        t0 = time.monotonic()
                        futs = [gb.submit(p, max_new_tokens=b)
                                for p, b in reqs]
                        toks = 0
                        for f in futs:
                            try:
                                toks += len(f.result(timeout=600).tokens)
                            except QueueFullError:
                                shed += 1
                        return toks, time.monotonic() - t0, shed
                    finally:
                        gb.close()

                run_once()  # warm executables AND the prefix tree
                toks, dt, shed = run_once()
                pinfo = eng.prefix_info()
                prefilled = max(
                    1, 2 * sum(p.shape[0] for p, _ in reqs)
                    - pinfo["hit_tokens"])
                rows.append({
                    "page_len": page_len, "pool_pages": pool_pages,
                    "pool": pool_label, "watermark": watermark,
                    "tokens": toks, "seconds": round(dt, 3),
                    "tokens_per_s": round(toks / dt, 1) if dt else 0.0,
                    "shed": shed,
                    "hit_token_ratio": round(
                        pinfo["hit_tokens"] / prefilled, 3),
                    "evictions": pinfo["evictions"],
                    "signatures": eng.cache_info()["size"]})
                r = rows[-1]
                print(f"page_len={page_len:<3} pool={pool_label:<12} "
                      f"wm={watermark:<5} {r['tokens_per_s']:8.1f} tok/s  "
                      f"hit_ratio={r['hit_token_ratio']:<6} "
                      f"shed={shed} evictions={r['evictions']}")
    best = max(rows, key=lambda r: (r["shed"] == 0, r["tokens_per_s"]))
    print("chosen config:")
    print(json.dumps({"chosen": {k: best[k] for k in
                                 ("page_len", "pool_pages", "pool",
                                  "watermark")},
                      "tokens_per_s": best["tokens_per_s"],
                      "hit_token_ratio": best["hit_token_ratio"],
                      "rows": rows}))


def placement_mode(seed: int = 5):
    """Placement-searcher sweep + a predicted-vs-measured closing loop.

    Two halves (docs/design.md §18):

    1. **Search grid** — model sizes x chip counts x traffic mixes on the
       TPU v5e inventory: one chosen ``PlacementPlan`` per cell, with the
       must-shard cells (params > one chip's HBM at tp=1) visible as the
       1-chip column going infeasible.
    2. **Predicted vs measured** — a real tiny LM export served by
       ``ShardedServingEngine`` on the host CPU mesh at tp in {1, 2, 4};
       the cost model runs on a HOST inventory whose peak FLOP/s is
       calibrated from a probe matmul first, so the predicted step time
       and the measured ``run_batch`` wall time are judged on the same
       hardware story. The ratio is printed per tp — the searcher's
       model is useful exactly insofar as this column stays near 1.

    Winner (best predicted QPS/chip across the grid) goes out as the
    final JSON line, the ``decode`` subcommand's format.
    """
    import json
    import os
    import tempfile

    # the virtual-device flag must land before jax's backends initialize
    flags_env = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags_env:
        os.environ["XLA_FLAGS"] = (
            flags_env + " --xla_force_host_platform_device_count=8").strip()
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import paddle_tpu as fluid
    from paddle_tpu import io
    from paddle_tpu.models.transformer import transformer_lm
    from paddle_tpu.serving.placement import (DeviceInventory, ModelProfile,
                                              NoFeasiblePlacement,
                                              PlacementSearcher,
                                              TrafficProfile, profile_export)
    from paddle_tpu.serving.sharded import ShardedServingEngine

    sizes = {
        "0.3b": ModelProfile.synthetic(24, 16, 1024, 4096, 32000, 2048),
        "7b": ModelProfile.synthetic(32, 32, 4096, 11008, 32000, 4096),
        "30b": ModelProfile.synthetic(48, 56, 7168, 28672, 32000, 4096),
    }
    mixes = {
        "interactive": [(1, 0.9), (4, 0.1)],
        "batchy": [(8, 0.5), (32, 0.5)],
    }
    chip_counts = (1, 4, 8, 16)
    rows = []
    print(f"{'model':<6}{'mix':<13}{'chips':>6}{'dp':>4}{'tp':>4}"
          f"{'hbm/dev':>9}{'qps/chip':>10}{'p95_ms':>9}  note")
    for mname, prof in sizes.items():
        for xname, mix in mixes.items():
            for chips in chip_counts:
                inv = DeviceInventory.tpu_v5e(chips)
                tr = TrafficProfile(mix, seq_len=min(2048,
                                                     prof.cfg["max_len"]))
                searcher = PlacementSearcher(prof, inv, tr)
                try:
                    p = searcher.search()
                except NoFeasiblePlacement:
                    print(f"{mname:<6}{xname:<13}{chips:>6}{'-':>4}{'-':>4}"
                          f"{'-':>9}{'-':>10}{'-':>9}  MUST-SHARD: no fit")
                    rows.append({"model": mname, "mix": xname,
                                 "chips": chips, "feasible": False})
                    continue
                rows.append({"model": mname, "mix": xname, "chips": chips,
                             "feasible": True, "dp": p.dp, "tp": p.tp,
                             "hbm_per_device_gb":
                                 round(p.hbm_bytes_per_device / 2**30, 3),
                             "qps_per_chip":
                                 round(p.predicted_qps_per_chip, 2),
                             "p95_ms": round(p.predicted_p95_ms, 2)})
                print(f"{mname:<6}{xname:<13}{chips:>6}{p.dp:>4}{p.tp:>4}"
                      f"{p.hbm_bytes_per_device / 2**30:>8.2f}G"
                      f"{p.predicted_qps_per_chip:>10.2f}"
                      f"{p.predicted_p95_ms:>9.2f}")

    # -- predicted vs measured on the real host mesh --
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_default_device", jax.devices("cpu")[0])
    V, T, D, H, L, FF = 512, 128, 64, 4, 2, 128
    # calibrate the host inventory's peak from a WORKLOAD-SHAPED probe
    # matmul ([B*T, D] @ [D, FF]): a 1024^3 probe hits BLAS peak rates the
    # model's thin matmuls never see, and the ratio column below is only
    # meaningful when predicted and measured share an achievable-rate story
    a = jnp.ones((8 * T, D), jnp.float32)
    w = jnp.ones((D, FF), jnp.float32)
    probe = jax.jit(lambda x, y: x @ y)
    jax.block_until_ready(probe(a, w))
    reps = 50
    t0 = time.perf_counter()
    for _ in range(reps):
        out = probe(a, w)
    jax.block_until_ready(out)
    gflops = reps * 2 * 8 * T * D * FF / (time.perf_counter() - t0) / 1e9
    d = os.path.join(tempfile.mkdtemp(prefix="perf_lab_placement_"), "lm")
    with fluid.unique_name.guard():
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            ids = fluid.layers.data("ids", shape=[T], dtype="int64")
            labels = fluid.layers.data("labels", shape=[T], dtype="int64")
            logits, _loss = transformer_lm(
                ids, labels, vocab_size=V, max_len=T, d_model=D, n_heads=H,
                n_layers=L, d_ff=FF)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope, seed=seed)
        io.save_inference_model(d, ["ids"], [logits], exe, main_prog,
                                scope=scope)
    prof = profile_export(d)
    rng = np.random.RandomState(seed)
    batch = 8
    feed = {"ids": rng.randint(0, V, (batch, T)).astype(np.int64)}
    print(f"\npredicted vs measured (CPU mesh, host inventory calibrated "
          f"at {gflops:.1f} GFLOP/s):")
    print("  (tp=1 judges the roofline terms; tp>1 ratios drift low on "
          "the CPU mesh because virtual-device all-gathers cost host "
          "microseconds the TPU link model prices in GB/s — the bench's "
          "collective-count contract, not this wall clock, is the tp "
          "acceptance gate)")
    print(f"{'tp':>4}{'measured_ms':>13}{'predicted_ms':>14}{'ratio':>8}")
    pv = []
    for tp in (1, 2, 4):
        inv = DeviceInventory.host(tp, peak_gflops=gflops)
        tr = TrafficProfile([(batch, 1.0)], seq_len=T)
        plan = PlacementSearcher(prof, inv, tr).score(1, tp)
        eng = ShardedServingEngine(d, dp=1, tp=tp, place=fluid.CPUPlace())
        eng.run_batch(feed)  # compile
        reps = 5
        t0 = time.perf_counter()
        for _ in range(reps):
            eng.run_batch(feed)
        measured_ms = (time.perf_counter() - t0) / reps * 1e3
        predicted_ms = plan.step_s * 1e3
        pv.append({"tp": tp, "measured_ms": round(measured_ms, 3),
                   "predicted_ms": round(predicted_ms, 3)})
        print(f"{tp:>4}{measured_ms:>13.3f}{predicted_ms:>14.3f}"
              f"{predicted_ms / measured_ms:>8.2f}")

    best = max((r for r in rows if r.get("feasible")),
               key=lambda r: r["qps_per_chip"])
    print("chosen config:")
    print(json.dumps({"chosen": {k: best[k] for k in
                                 ("model", "mix", "chips", "dp", "tp")},
                      "qps_per_chip": best["qps_per_chip"],
                      "predicted_vs_measured": pv,
                      "rows": rows}))


def _train_child(argv):
    """One train_scale cell, run in a FRESH process: `perf_lab.py
    train-child DP ACCUM ZERO WINDOWS K GLOBAL_BATCH [TP PP MICRO]`.
    Fresh because the forced virtual-device count (dp*tp*pp) must land
    before jax initializes and must never perturb the other lanes'
    thread pools (the PR-8 --mesh trick). Prints ONE JSON line the
    parent collects."""
    import json
    import os

    dp, accum, zero, windows, k, gb = (int(a) for a in argv[:6])
    tp = int(argv[6]) if len(argv) > 6 else 1
    pp = int(argv[7]) if len(argv) > 7 else 1
    micro = int(argv[8]) if len(argv) > 8 else 0
    flags_env = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags_env:
        os.environ["XLA_FLAGS"] = (
            flags_env + f" --xla_force_host_platform_device_count="
            f"{max(dp * tp * pp, 1)}").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.models.transformer import transformer_lm
    from paddle_tpu.parallel.ddp import ShardedTrainStep

    V, T, D, H, L, FF = 512, 32, 64, 4, 2, 128
    with fluid.unique_name.guard():
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            ids = fluid.layers.data("ids", shape=[T], dtype="int64")
            labels = fluid.layers.data("labels", shape=[T], dtype="int64")
            if pp > 1:
                _, loss = transformer_lm(
                    ids, labels, vocab_size=V, max_len=T, d_model=D,
                    n_heads=H, n_layers=L, d_ff=FF, pp_stages=pp,
                    pp_microbatches=micro or None, tp_shard=tp > 1)
            else:
                _, loss = transformer_lm(ids, labels, vocab_size=V,
                                         max_len=T, d_model=D, n_heads=H,
                                         n_layers=L, d_ff=FF)
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss, startup)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope, seed=11)
    sts = ShardedTrainStep(main_prog, dp=dp, accum_steps=accum,
                           zero_stage=zero, tp=tp, pp=pp,
                           pp_microbatches=micro or None, executor=exe)
    rng = np.random.RandomState(5)
    X = rng.randint(0, V, (gb, T)).astype(np.int64)
    feed = {"ids": X, "labels": X}
    # one warm window: run_steps commits state arrays to the executor
    # device, so the delegate path compiles exactly once per signature
    # and the timed cells compare steady states across dp
    out = sts.run_window(feed, k=k, fetch_list=[loss], scope=scope)
    t0 = time.perf_counter()
    for _ in range(windows):
        out = sts.run_window(feed, k=k, fetch_list=[loss], scope=scope)
    step_s = (time.perf_counter() - t0) / (windows * k)
    res = sts.state_bytes_per_device(scope)
    print(json.dumps({
        "dp": dp, "accum": accum, "zero_stage": zero,
        "tp": tp, "pp": pp, "pp_schedule": sts.pp_schedule,
        "global_batch": gb, "k": k,
        "step_ms": round(step_s * 1e3, 3),
        "rows_per_sec": round(gb / step_s, 1),
        "rows_per_sec_per_chip": round(gb / step_s / (dp * tp * pp), 1),
        "loss_final": float(np.asarray(out[0]).mean()),
        "opt_shard_bytes_per_device": res["opt_shard_bytes_per_device"],
        "zero_account_bytes": res["zero_account_bytes"],
    }))


def train_scale_mode(windows: int = 4, k: int = 2, global_batch: int = 32):
    """`perf_lab.py train_scale` — sweep dp x tp x pp x zero_stage (and
    accum on the pure-dp lanes) in fresh subprocesses (each child forces
    its own virtual-device count dp*tp*pp before jax initializes — the
    PR-8 --mesh discipline, so the forced mesh never perturbs other
    lanes), print the table, and emit the winner (max rows/s/chip at
    the fixed global batch, ties to the simpler config) as the final
    JSON line. The grid mirrors docs/design.md §27's failure matrix:
    zero-3 needs dp>=2; pp lanes run zero=1/accum=1 (the microbatch
    schedule IS the accumulation window)."""
    import json
    import os
    import subprocess

    here = os.path.abspath(__file__)
    env = {key: v for key, v in os.environ.items() if key != "PYTHONPATH"}
    env.pop("XLA_FLAGS", None)  # each child forces its own device count
    env["JAX_PLATFORMS"] = "cpu"
    # (dp, accum, zero, tp, pp, microbatches)
    grid = [(dp, accum, zero, 1, 1, 0)
            for dp in (1, 2, 4, 8)
            for accum in (1, 2, 4)
            for zero in (1, 2)
            if global_batch % (dp * accum) == 0
            and not (dp == 1 and zero == 2 and accum == 1)]
    # zero-3 bucketed-prefetch lanes (dp>=2, accum=1)
    grid += [(dp, 1, 3, 1, 1, 0) for dp in (2, 4, 8)]
    # tensor-parallel lanes (Path A: column-sharded weights in-window)
    grid += [(1, 1, 1, 2, 1, 0), (2, 1, 1, 2, 1, 0), (2, 1, 3, 2, 1, 0)]
    # pipeline lanes: M=2*pp -> gpipe, M=8 > 2*pp -> 1f1b
    grid += [(1, 1, 1, 1, 2, 4), (2, 1, 1, 1, 2, 8), (1, 1, 1, 2, 2, 8)]
    rows = []
    print(f"{'dp':>4}{'tp':>4}{'pp':>4}{'accum':>7}{'zero':>6}"
          f"{'step_ms':>9}{'rows/s':>9}{'rows/s/chip':>13}"
          f"{'opt_B/dev':>11}{'sched':>7}  note")
    for dp, accum, zero, tp, pp, micro in grid:
        r = subprocess.run(
            [sys.executable, here, "train-child", str(dp), str(accum),
             str(zero), str(windows), str(k), str(global_batch),
             str(tp), str(pp), str(micro)],
            capture_output=True, text=True, env=env, timeout=900)
        if r.returncode != 0:
            print(f"{dp:>4}{tp:>4}{pp:>4}{accum:>7}{zero:>6}{'-':>9}"
                  f"{'-':>9}{'-':>13}{'-':>11}{'-':>7}  "
                  f"FAILED: {(r.stderr or '')[-120:]}")
            continue
        rec = json.loads(r.stdout.strip().splitlines()[-1])
        rows.append(rec)
        print(f"{dp:>4}{tp:>4}{pp:>4}{accum:>7}{zero:>6}"
              f"{rec['step_ms']:>9.3f}{rec['rows_per_sec']:>9.1f}"
              f"{rec['rows_per_sec_per_chip']:>13.1f}"
              f"{int(rec['opt_shard_bytes_per_device']):>11}"
              f"{rec.get('pp_schedule') or '-':>7}")
    if not rows:
        print(json.dumps({"error": "every train_scale cell failed"}))
        sys.exit(1)
    best = max(rows, key=lambda r: (r["rows_per_sec_per_chip"],
                                    -r["dp"], -r.get("tp", 1),
                                    -r.get("pp", 1), -r["accum"],
                                    -r["zero_stage"]))
    print("chosen config:")
    print(json.dumps({"chosen": {key: best[key] for key in
                                 ("dp", "tp", "pp", "accum",
                                  "zero_stage")},
                      "step_ms": best["step_ms"],
                      "rows_per_sec_per_chip":
                          best["rows_per_sec_per_chip"],
                      "rows": rows}))


def _resilience_child(argv):
    """One resilience cell, run in a FRESH process: `perf_lab.py
    resilience-child EVERY SYNC WINDOWS STEPS`. Fresh because each cell
    spins its own snapshot publisher thread and flips the process
    goodput accountant — neither may leak across cells. Prints ONE JSON
    line the parent collects."""
    import json
    import os
    import tempfile

    every, sync, windows, steps = (int(a) for a in argv[:4])
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.obs.goodput import get_accountant
    from paddle_tpu.parallel import CheckpointPolicy, ResilientTrainer

    DIM, HID, B = 64, 256, 64
    with fluid.unique_name.guard():
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            x = fluid.layers.data("x", shape=[DIM], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            h = fluid.layers.fc(x, size=HID, act="relu")
            pred = fluid.layers.fc(h, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.05).minimize(loss, startup)

    def feed_fn(w):
        rng = np.random.RandomState(900 + w)
        X = rng.randn(B, DIM).astype(np.float32)
        return {"x": X, "y": (X[:, :1] * 0.25).astype(np.float32)}

    acct = get_accountant()
    acct.enable()
    with tempfile.TemporaryDirectory(prefix="pt_resilience_") as ckdir:
        rt = ResilientTrainer(
            main_prog, checkpoint_dir=ckdir, feed_fn=feed_fn,
            loss_name=loss.name, executor=fluid.Executor(fluid.CPUPlace()),
            scope=fluid.Scope(), startup_program=startup, seed=11,
            window_steps=steps,
            policy=CheckpointPolicy(every_windows=every, sync=bool(sync)))
        # one warm window (compile) outside the measured span, then the
        # measured windows — cadence cells compare steady states
        recs = rt.run(1 + windows)[1:]
        rt.close()
    acct.disable()

    ckpt_s = sum(r["goodput"]["train"]["categories"].get("checkpoint", 0.0)
                 for r in recs)
    wall_s = sum(r["goodput"]["wall_s"] for r in recs)
    print(json.dumps({
        "every_windows": every, "sync": bool(sync),
        "ckpt_ms_per_window": round(ckpt_s / windows * 1e3, 4),
        "wall_ms_per_window": round(wall_s / windows * 1e3, 4),
        "badput_frac": round(ckpt_s / wall_s, 6) if wall_s > 0 else 1.0,
        "snapshots": sum(1 for r in recs if r.get("serial") is not None),
    }))


def resilience_mode(windows: int = 8, steps: int = 8):
    """`perf_lab.py resilience` — sweep snapshot cadence x async-vs-sync
    in fresh subprocesses, print the exposed goodput `checkpoint` seconds
    per window for each cell, and emit the winner (lowest checkpoint
    badput among the cells that still snapshot every window, ties to
    async) as the final JSON line. The point of the table is the ISSUE-17
    claim made measurable: the async double buffer's exposed cost is the
    device->host copy alone, so its badput should sit an order of
    magnitude under the sync cell at equal cadence."""
    import json
    import os
    import subprocess

    here = os.path.abspath(__file__)
    env = {key: v for key, v in os.environ.items() if key != "PYTHONPATH"}
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    grid = [(every, sync) for every in (1, 2, 4) for sync in (0, 1)]
    rows = []
    print(f"{'every':>6}{'mode':>7}{'ckpt_ms/win':>13}{'wall_ms/win':>13}"
          f"{'badput':>9}{'saves':>7}")
    for every, sync in grid:
        r = subprocess.run(
            [sys.executable, here, "resilience-child", str(every),
             str(sync), str(windows), str(steps)],
            capture_output=True, text=True, env=env, timeout=900)
        if r.returncode != 0:
            print(f"{every:>6}{'sync' if sync else 'async':>7}{'-':>13}"
                  f"{'-':>13}{'-':>9}{'-':>7}  FAILED: "
                  f"{(r.stderr or '')[-120:]}")
            continue
        rec = json.loads(r.stdout.strip().splitlines()[-1])
        rows.append(rec)
        print(f"{every:>6}{'sync' if sync else 'async':>7}"
              f"{rec['ckpt_ms_per_window']:>13.4f}"
              f"{rec['wall_ms_per_window']:>13.4f}"
              f"{rec['badput_frac']:>9.4f}{rec['snapshots']:>7}")
    if not rows:
        print(json.dumps({"error": "every resilience cell failed"}))
        sys.exit(1)
    # the winner must keep the every-window cadence (the durability the
    # ISSUE demands) — cheaper cadences are shown for the tradeoff table,
    # not eligible to win
    eligible = [r for r in rows if r["every_windows"] == 1] or rows
    best = min(eligible, key=lambda r: (r["badput_frac"], r["sync"]))
    print("chosen config:")
    print(json.dumps({"chosen": {"every_windows": best["every_windows"],
                                 "sync": best["sync"]},
                      "ckpt_ms_per_window": best["ckpt_ms_per_window"],
                      "badput_frac": best["badput_frac"],
                      "rows": rows}))


def _cpu_child(argv):
    """One sweep cell, run in a FRESH process: `perf_lab.py cpu-child
    EXPORT QUANT THREADS MAX_BATCH REPS`. A fresh process because the
    XLA_FLAGS half of the thread shaping is read once at CPU backend
    creation — in this child no computation has run yet, so
    ``serving/quant.apply_cpu_flags`` (the ONE thread-shaping
    implementation) still lands its env edit before the lazy backend
    comes up. Prints ONE JSON line the parent collects."""
    import json
    import os

    export, quant, threads, max_batch, reps = (
        argv[0], argv[1], int(argv[2]), int(argv[3]), int(argv[4]))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving.quant import (QuantizedServingEngine,
                                          apply_cpu_flags)

    if threads > 0:
        assert apply_cpu_flags(threads=threads), \
            "cpu-child: backend initialized before thread shaping"

    buckets = [b for b in (1, 2, 4, 8, 16, 32) if b <= max_batch]
    if quant == "f32":
        eng = ServingEngine(export, place=fluid.CPUPlace(),
                            batch_buckets=buckets)
    else:
        eng = QuantizedServingEngine(export, mode=quant,
                                     place=fluid.CPUPlace(),
                                     batch_buckets=buckets)
    var = eng._feed_vars[eng.feed_names[0]]
    t = int(var.shape[1])
    if hasattr(eng, "cfg"):
        vocab = eng.cfg["vocab"]
    else:  # plain f32 engine: recover the vocab from the IR walk
        from paddle_tpu.models.transformer import decode_roles

        vocab = decode_roles(eng.program)[1]["vocab"]
    rng = np.random.RandomState(0)
    full = {eng.feed_names[0]:
            rng.randint(0, vocab, (max_batch, t)).astype(np.int64)}
    one = {eng.feed_names[0]:
           rng.randint(0, vocab, (1, t)).astype(np.int64)}
    for feeds in (full, one):  # compile both measured buckets
        eng.run_batch(feeds)
        eng.run_batch(feeds)
    t0 = time.perf_counter()
    for _ in range(reps):
        eng.run_batch(full)
    bucket_s = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        eng.run_batch(one)
    one_s = (time.perf_counter() - t0) / reps
    print(json.dumps({
        "quantize": quant, "threads": threads, "max_batch": max_batch,
        "qps": round(max_batch / bucket_s, 2),
        "row_ms": round(one_s * 1e3, 3),
        "weights_bytes": eng.weights_bytes()}))


def cpu_mode():
    """`perf_lab.py cpu [EXPORT_DIR]` — the CPU serving tuning sweep
    (docs/design.md §20): threads x weight-only quant mode x bucket
    ladder, every cell a fresh subprocess (thread flags are pre-jax-init
    only), closed-loop QPS at the full bucket as the score. The chosen
    config is written to the export's ``cpu_tuned.json`` ONLY when it
    beats the untuned f32 baseline by >5% closed-loop (the PR-4 autotune
    adoption bar) AND, for quantized candidates, greedy-token agreement
    holds the quantize_export floor — `ServingServer(quantize="auto")`
    then adopts it. Final line: the chosen config as JSON."""
    import json
    import os
    import subprocess
    import tempfile

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    export = sys.argv[2] if len(sys.argv) > 2 else None
    if export is None:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        # the ONE pinned-export builder bench.py's cpu_quantized workload
        # shares — the bar and this sweep must measure the same model
        from paddle_tpu.models.transformer import train_successor_lm_export

        export = os.path.join(tempfile.mkdtemp(prefix="perf_lab_cpu_"), "lm")
        print(f"no export given: training the pinned successor-task LM "
              f"(confident greedy margins — the agreement gate needs a "
              f"trained model) -> {export}")
        train_successor_lm_export(export)

    from paddle_tpu.serving.quant import (ADOPTION_MIN_WIN,
                                          DEFAULT_AGREEMENT_FLOOR,
                                          calibrate_error,
                                          write_tuned_config)

    # quantized candidates must hold the accuracy contract to be adoptable
    agreement = {}
    for mode in ("int8", "bf16"):
        rep = calibrate_error(export, mode=mode)
        agreement[mode] = rep["token_agreement"]
        print(f"calibration {mode}: token agreement "
              f"{rep['token_agreement']:.4f}, max abs logit err "
              f"{rep['max_abs_logit_err']:.3e}")

    ncpu = os.cpu_count() or 1
    # 0 = backend default pool, 1 = single-threaded Eigen (a DISTINCT
    # config even on a 1-core host — the flag changes the threadpool
    # machinery, not just its width), ncpu = full width when it differs
    thread_grid = sorted({0, 1} | ({ncpu} if ncpu > 1 else set()))
    quant_grid = ("f32", "int8", "bf16")
    batch_grid = (4, 8, 16)
    reps = int(os.environ.get("PERF_LAB_CPU_REPS", "30"))
    here = os.path.abspath(__file__)
    rows = []
    print(f"{'quant':<6}{'threads':>8}{'max_batch':>10}{'qps':>10}"
          f"{'row_ms':>9}{'weights':>12}")
    for quant in quant_grid:
        for threads in thread_grid:
            for mb in batch_grid:
                try:
                    r = subprocess.run(
                        [sys.executable, here, "cpu-child", export, quant,
                         str(threads), str(mb), str(reps)],
                        capture_output=True, text=True, timeout=600)
                except subprocess.TimeoutExpired:
                    # one slow cell is a FAILED row, not a lost sweep —
                    # the rows already measured still decide adoption
                    print(f"{quant:<6}{threads:>8}{mb:>10}  FAILED: "
                          f"timed out after 600s")
                    continue
                if r.returncode != 0:
                    print(f"{quant:<6}{threads:>8}{mb:>10}  FAILED: "
                          f"{(r.stderr or '')[-120:]}")
                    continue
                rec = json.loads(r.stdout.strip().splitlines()[-1])
                rows.append(rec)
                print(f"{quant:<6}{threads:>8}{mb:>10}{rec['qps']:>10.1f}"
                      f"{rec['row_ms']:>9.3f}{rec['weights_bytes']:>12}")
    base = next((r for r in rows if r["quantize"] == "f32"
                 and r["threads"] == 0 and r["max_batch"] == 8), None)
    eligible = [r for r in rows
                if r["quantize"] == "f32"
                or agreement.get(r["quantize"], 0.0)
                >= DEFAULT_AGREEMENT_FLOOR]
    best = max(eligible, key=lambda r: r["qps"]) if eligible else None
    out = {"export": export, "baseline": base, "best": best, "rows": rows}
    if base and best and best is not base:
        win = best["qps"] / base["qps"] - 1.0
        out["win"] = round(win, 4)
        if win > ADOPTION_MIN_WIN:
            cfg = {"quantize": None if best["quantize"] == "f32"
                   else best["quantize"],
                   "threads": best["threads"],
                   "max_batch_size": best["max_batch"],
                   "win": round(win, 4),
                   "baseline_qps": base["qps"], "qps": best["qps"],
                   "agreement": agreement.get(best["quantize"]),
                   "host_cpus": ncpu}
            path = write_tuned_config(export, cfg)
            out["adopted"] = cfg
            print(f"ADOPTED (+{win:.1%} closed-loop > "
                  f"{ADOPTION_MIN_WIN:.0%} bar): {path}")
        else:
            print(f"NOT adopted: best win {win:+.1%} is under the "
                  f"{ADOPTION_MIN_WIN:.0%} bar — measurement says the "
                  f"untuned f32 baseline stands on this host")
    print(json.dumps(out))


def _spec_child(argv):
    """One speculative-decoding sweep cell in a FRESH process:
    `perf_lab.py spec-child TARGET DRAFT K MAX_SLOTS dense|paged N_REQS`.
    A fresh process so every cell measures a cold-warmed engine pair —
    compile caches, draft state, and acceptance EMAs never leak between
    cells. K=0 is the vanilla (no-spec) lane. Prints ONE JSON line."""
    import json
    import os

    target, draft = argv[0], argv[1]
    k, max_slots = int(argv[2]), int(argv[3])
    paged, n_reqs = argv[4] == "paged", int(argv[5])
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import numpy as np

    from paddle_tpu.serving.decode import DecodeEngine, GenerationBatcher
    from paddle_tpu.serving.kvcache import PagedDecodeEngine
    from paddle_tpu.serving.spec import SpecDecoder

    eng_cls = PagedDecodeEngine if paged else DecodeEngine
    eng = eng_cls(target, max_slots=max_slots)
    spec = SpecDecoder(draft, k=k, adaptive=False) if k > 0 else None
    b = GenerationBatcher(eng, spec=spec, start=False)
    if spec is not None:
        spec.warmup()
    eng.warmup()
    b.start()
    vocab = eng.cfg["vocab"]
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, vocab, size=(int(rng.randint(2, 10)),))
               for _ in range(n_reqs)]
    misses0 = eng.cache_misses + (spec.draft.cache_misses if spec else 0)
    t0 = time.perf_counter()
    futs = [b.submit(p, max_new_tokens=24) for p in prompts]
    toks = sum(len(f.result(timeout=300).tokens) for f in futs)
    dt = time.perf_counter() - t0
    recompiles = (eng.cache_misses
                  + (spec.draft.cache_misses if spec else 0) - misses0)
    b.close()
    print(json.dumps({
        "k": k, "max_slots": max_slots,
        "engine": "paged" if paged else "dense",
        "tokens": toks, "tokens_per_s": round(toks / dt, 2),
        "acceptance": (round(spec.acceptance_rate, 4)
                       if spec is not None else None),
        "recompiles": recompiles}))


def spec_mode():
    """`perf_lab.py spec [TARGET_EXPORT [DRAFT_EXPORT]]` — the speculative
    decoding sweep (docs/design.md §25): draft depth k x slot count x
    dense/paged KV, every cell a FRESH subprocess over the same export
    pair, greedy closed-loop tokens/s as the score. k=0 rows are the
    vanilla baselines; the winner is the best speculative cell and its
    ratio is taken against the vanilla row with the SAME slot count and
    engine (spec must beat its own lane, not a strawman). A cell that
    steady-state-recompiles is disqualified — the zero-recompile contract
    is part of the score, not a footnote. Final line: winner JSON."""
    import json
    import os
    import subprocess
    import tempfile

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    target = sys.argv[2] if len(sys.argv) > 2 else None
    draft = sys.argv[3] if len(sys.argv) > 3 else None
    if target is None or draft is None:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from paddle_tpu.models.transformer import train_successor_lm_export

        root = tempfile.mkdtemp(prefix="perf_lab_spec_")
        if target is None:
            target = os.path.join(root, "target")
            print(f"no target export given: training the pinned "
                  f"successor-task LM -> {target}")
            train_successor_lm_export(target, vocab_size=128, max_len=48,
                                      d_model=64, d_ff=256, steps=80)
        if draft is None:
            draft = os.path.join(root, "draft")
            print(f"no draft export given: training a 1-layer draft on "
                  f"the same task -> {draft}")
            train_successor_lm_export(draft, vocab_size=128, max_len=48,
                                      d_model=32, n_layers=1, d_ff=128,
                                      steps=80)

    n_reqs = int(os.environ.get("PERF_LAB_SPEC_REQS", "12"))
    here = os.path.abspath(__file__)
    rows = []
    print(f"{'engine':<7}{'slots':>6}{'k':>4}{'tok/s':>10}{'accept':>9}"
          f"{'recompiles':>12}")
    for engine in ("dense", "paged"):
        for slots in (2, 4):
            for k in (0, 2, 4):
                try:
                    r = subprocess.run(
                        [sys.executable, here, "spec-child", target, draft,
                         str(k), str(slots), engine, str(n_reqs)],
                        capture_output=True, text=True, timeout=600)
                except subprocess.TimeoutExpired:
                    print(f"{engine:<7}{slots:>6}{k:>4}  FAILED: timed out "
                          f"after 600s")
                    continue
                if r.returncode != 0:
                    print(f"{engine:<7}{slots:>6}{k:>4}  FAILED: "
                          f"{(r.stderr or '')[-120:]}")
                    continue
                rec = json.loads(r.stdout.strip().splitlines()[-1])
                rows.append(rec)
                acc = rec["acceptance"]
                print(f"{engine:<7}{slots:>6}{k:>4}"
                      f"{rec['tokens_per_s']:>10.1f}"
                      f"{acc if acc is not None else '-':>9}"
                      f"{rec['recompiles']:>12}")
    base = {(r["engine"], r["max_slots"]): r for r in rows if r["k"] == 0}
    candidates = [r for r in rows if r["k"] > 0 and r["recompiles"] == 0
                  and (r["engine"], r["max_slots"]) in base]
    out = {"target": target, "draft": draft, "rows": rows, "winner": None}
    if candidates:
        best = max(candidates, key=lambda r: r["tokens_per_s"])
        b = base[(best["engine"], best["max_slots"])]
        out["winner"] = dict(best,
                             vanilla_tokens_per_s=b["tokens_per_s"],
                             ratio=round(best["tokens_per_s"]
                                         / b["tokens_per_s"], 3))
        print(f"winner: {best['engine']} slots={best['max_slots']} "
              f"k={best['k']} -> {best['tokens_per_s']:.1f} tok/s "
              f"(x{out['winner']['ratio']:.2f} vs its vanilla lane, "
              f"acceptance {best['acceptance']:.2%})")
    else:
        print("no eligible speculative cell (all failed or recompiled)")
    print(json.dumps(out))


#: dW sweep adoption bar — the PR-4 discipline (serving/quant.py spells the
#: same 5% for the CPU lane); a win inside the slope's noise is weather
TUNE_MARGIN = 0.95
#: flash schedule shapes the sweep targets: the bench transformer layer
#: (the probe_fa_gap-measured ~3x short-sequence tax) and the longcontext
#: layer — (B, H, T, D)
TUNE_FLASH_SHAPES = ((8, 8, 1024, 128), (1, 8, 4096, 128))


def tune_mode():
    """`perf_lab.py tune [DB_PATH]` — the offline kernel-tuning sweep
    (docs/design.md §21), the populator of the persistent TuningDB that
    the op registry consults at lowering time.

    Search space: every audited dW shape (bench + longcontext + remat
    sets) x {direct, transpose} x the traffic model's top-3 ranked block
    plans (the planner is a model; its runners-up get to be measured),
    plus the flash-attention schedule surface (q_block x k_block x
    heads_per_block via tools/probe_fa_gap.sweep — the kernel-level probe
    this sweep builds on). Every candidate is slope-timed on-chip with
    the shared chained-window instrument; a config is ADOPTED only on a
    >5% win over its stock baseline (XLA's dW lowering / the 512-block
    flash default — the PR-4 discipline), and every negative is recorded
    too, so the r4/r5 hand-kept ledger of negatives is generated from
    here on. On a non-TPU backend nothing is measured or recorded —
    on-chip A/Bs on an interpreter are noise dressed as data — but the
    search space is printed so the command is inspectable anywhere.
    Final line: the sweep summary as JSON (decode-mode format)."""
    import json
    import os

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "."))
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import probe_fa_gap

    from paddle_tpu import tune
    from paddle_tpu.ops import pallas_attention, pallas_matmul
    from paddle_tpu.ops.pallas_attention import _interpret_default

    # default DB: the repo-root TUNE_DB.json bench.py warms its rounds from
    db_path = sys.argv[2] if len(sys.argv) > 2 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "TUNE_DB.json")
    dw_shapes = (pallas_matmul.BENCH_DW_SHAPES + pallas_matmul.LC_DW_SHAPES
                 + pallas_matmul.LCR_DW_SHAPES)
    print(f"tune sweep -> {db_path}")
    print(f"  dW shapes: {len(dw_shapes)} x (2 strategies x <=3 block "
          f"plans); flash shapes: {len(TUNE_FLASH_SHAPES)}")
    if _interpret_default():
        print("no TPU backend: the tuning sweep is an ON-CHIP measurement "
              "and records nothing here (PR-4 discipline). Search space:")
        for (m, n, k) in dw_shapes:
            cands = pallas_matmul.plan_candidates(m, n, k)
            print(f"  dw_matmul ({m},{n},{k}): direct/transpose x "
                  f"{[tuple(c) for c in cands]}")
        for (b, h, t, d) in TUNE_FLASH_SHAPES:
            cands = pallas_attention.flash_candidates(t, h, d)
            print(f"  flash_attention (T={t},H={h},D={d}): "
                  f"{len(cands)} schedule candidates")
        print(json.dumps({"db": db_path, "measured": False,
                          "adopted": [], "rejected": []}))
        return

    tune.configure(path=db_path, readonly=False)
    adopted, rejected = [], []

    def decide(op, shape, dtype, baseline_ms, best_name, best_ms, config,
               slopes, source):
        win = 1.0 - best_ms / baseline_ms
        adopt = best_ms < TUNE_MARGIN * baseline_ms
        tune.record(op, shape, dtype,
                    decision="adopt" if adopt else "reject",
                    config=config if adopt else None,
                    baseline_ms=baseline_ms, best_ms=best_ms,
                    slopes=slopes, source=source,
                    save=False)  # batched: one flush below, not N rewrites
        row = {"op": op, "shape": list(shape), "best": best_name,
               "win": round(win, 4)}
        (adopted if adopt else rejected).append(row)
        print(f"  {'ADOPT ' if adopt else 'reject'} {op} {shape}: "
              f"{best_name} {best_ms:.3f}ms vs baseline "
              f"{baseline_ms:.3f}ms ({win:+.1%})")

    for (m, n, k) in dw_shapes:
        cands = {}
        plans = pallas_matmul.plan_candidates(m, n, k)
        for strategy in ("direct", "transpose"):
            cands[strategy] = (strategy, None)  # the planner's own pick
            for p in plans[1:]:                 # measured runners-up
                bm, bn, bk = p
                cands[f"{strategy}@{bm}x{bn}x{bk}"] = (strategy,
                                                       (bm, bn, bk))
        try:
            res = pallas_matmul.measure_candidates(m, n, k, cands)
        except Exception as e:
            print(f"  dw_matmul ({m},{n},{k}) FAILED: {e}")
            continue
        best_name = min((c for c in res if c != "xla"), key=res.get)
        strategy, blocks = cands[best_name]
        decide("dw_matmul", (m, n, k), "bfloat16", res["xla"],
               best_name, res[best_name],
               {"strategy": strategy,
                "blocks": list(blocks) if blocks else None},
               {name: round(v, 4) for name, v in res.items()},
               "perf_lab tune")

    for (b, h, t, d) in TUNE_FLASH_SHAPES:
        try:
            base_ms, rows = probe_fa_gap.sweep(b, h, t, d)
        except Exception as e:
            print(f"  flash_attention (T={t},H={h},D={d}) FAILED: {e}")
            continue
        if not rows:
            continue
        best = rows[0]
        decide("flash_attention", pallas_attention.flash_key(t, h, d),
               "bfloat16", base_ms, json.dumps(best["config"],
                                               sort_keys=True),
               best["fwd_bwd_ms"], dict(best["config"]),
               {json.dumps(r["config"], sort_keys=True): r["fwd_bwd_ms"]
                for r in rows},
               "perf_lab tune (probe_fa_gap sweep)")

    tune.flush()  # ONE merge+publish for the whole sweep
    print(json.dumps({"db": db_path, "measured": True,
                      "adopted": adopted, "rejected": rejected}))


def main():
    layout = sys.argv[1] if len(sys.argv) > 1 else "nchw"
    if layout == "pipeline":
        pipeline_mode()
        return
    if layout == "decode":
        decode_mode()
        return
    if layout == "kv":
        kv_mode()
        return
    if layout == "placement":
        placement_mode()
        return
    if layout == "cpu":
        cpu_mode()
        return
    if layout == "cpu-child":
        _cpu_child(sys.argv[2:])
        return
    if layout == "spec":
        spec_mode()
        return
    if layout == "spec-child":
        _spec_child(sys.argv[2:])
        return
    if layout == "train_scale":
        train_scale_mode()
        return
    if layout == "train-child":
        _train_child(sys.argv[2:])
        return
    if layout == "resilience":
        resilience_mode()
        return
    if layout == "resilience-child":
        _resilience_child(sys.argv[2:])
        return
    if layout == "tune":
        tune_mode()
        return
    rng = np.random.RandomState(0)
    params, blocks = init_params(rng, layout)
    dev = jax.devices()[0]
    params = jax.device_put(params, dev)
    img = jax.device_put(rng.randn(BATCH, 3, IMAGE, IMAGE).astype(np.float32), dev)
    label = jax.device_put(rng.randint(0, CLASSES, (BATCH, 1)), dev)
    velo = jax.tree.map(jnp.zeros_like, params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, velo, img, label):
        loss, grads = jax.value_and_grad(
            lambda p: forward(p, blocks, img, label, layout))(params)
        velo = jax.tree.map(lambda v, g: 0.9 * v + g, velo, grads)
        params = jax.tree.map(lambda p, v: p - 0.1 * v, params, velo)
        return params, velo, loss

    for _ in range(5):
        params, velo, loss = step(params, velo, img, label)
    float(loss)

    def run_n(n):
        nonlocal params, velo
        t0 = time.perf_counter()
        for _ in range(n):
            params, velo, loss = step(params, velo, img, label)
        float(loss)
        return time.perf_counter() - t0

    t1, t2 = run_n(10), run_n(50)
    dt = (t2 - t1) / 40
    img_s = BATCH / dt
    mfu = img_s * GFLOP_PER_IMG / 1e3 / PEAK_TFLOPS
    print(f"pure-jax resnet50 {layout}: {img_s:.1f} img/s  "
          f"step {dt*1e3:.2f} ms  MFU {mfu*100:.1f}%")


if __name__ == "__main__":
    main()

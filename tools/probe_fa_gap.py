"""Flash-attention schedule-gap probe (VERDICT r5 item 4).

The r5 audit measured the bench-config flash kernels (T=1024, 512-token
blocks -> a 2-cell-per-(batch*head) grid) at 8.2 ms/step against a
~2.2-2.9 ms causal-analytic floor — a ~3x "small-grid tax" attributed to
per-cell VPU softmax + DMA that the tiny sequential grid cannot amortize.
This probe bounds that claim cheaply: it slope-times ONE layer's flash
fwd+bwd at the bench shape (B8 H8 T1024 D128) and at the longcontext
shape (B1 H8 T4096 D128, an 8x longer K loop per cell) and prints each
against its own analytic floor. If the tax ratio falls materially at
T=4096, the gap is T=1024-specific (amortization), not a kernel-schedule
defect — and the perf.md sentence "only a materially different schedule
could attack it" gets scoped to short sequences.

Floor model: 8 MXU passes/layer (2 fwd + 6 bwd, the FA-2 recipe — the
QK^T replay runs in BOTH backward kernels), each 2*B*H*(T^2/2)*D FLOPs
causal, at the chip's measured 190 TF/s big-matmul rate.

Usage: python tools/probe_fa_gap.py [B,H,T,D ...]
"""
import json
import sys

sys.path.insert(0, ".")
import numpy as np  # noqa: E402

MEASURED_PEAK_TFS = 190.0  # tools/perf_lab.py big-matmul rate
CONFIGS = ((8, 8, 1024, 128),   # bench transformer layer (r5: 8.2ms/8 layers)
           (1, 8, 4096, 128))   # longcontext layer


def floor_ms(b, h, t, d):
    flops = 8 * 2 * b * h * (t * t / 2) * d
    return flops / (MEASURED_PEAK_TFS * 1e12) * 1e3


def measure(b, h, t, d, iters=8, reps=3):
    """One layer's flash fwd+bwd ms via the shared chained-window slope
    (profiler.chained_slope_ms — the same instrument pallas_matmul's
    autotune uses; the q-scaling chain keeps XLA from hoisting or DCE'ing
    the loop-invariant kernel calls)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from paddle_tpu.ops.pallas_attention import flash_attention
    from paddle_tpu.profiler import chained_slope_ms

    rng = np.random.RandomState(0)
    q0 = jnp.asarray(rng.randn(b, t, h, d), jnp.bfloat16)

    def step(q):
        out, vjp = jax.vjp(
            lambda q: flash_attention(q, q, q, True, None, 512, 512), q)
        (dq,) = vjp(out)
        return dq

    def window(n):
        @jax.jit
        def run(q):
            def body(_, carry):
                q, s = carry
                dq = step(q)
                s = dq[0, 0, 0, 0].astype(jnp.float32)
                q = q * (1.0 + s * 1e-30).astype(q.dtype)
                return q, s
            _, s = lax.fori_loop(0, n, body, (q, jnp.float32(0.0)))
            return s
        return run

    return chained_slope_ms(window, iters=iters, reps=reps, args=(q0,))


if __name__ == "__main__":
    configs = ([tuple(int(x) for x in s.split(",")) for s in sys.argv[1:]]
               or CONFIGS)
    for (b, h, t, d) in configs:
        ms = measure(b, h, t, d)
        fl = floor_ms(b, h, t, d)
        print(json.dumps({
            "config": {"B": b, "H": h, "T": t, "D": d},
            "fwd_bwd_ms": round(ms, 3),
            "analytic_floor_ms": round(fl, 3),
            "tax_ratio": round(ms / fl, 2),
            "grid_cells_per_bh": t // 512 if t >= 512 else 1,
        }), flush=True)

"""Flash-attention schedule-gap probe (VERDICT r5 item 4; sweep in PR 12).

The r5 audit measured the bench-config flash kernels (T=1024, 512-token
blocks -> a 2-cell-per-(batch*head) grid) at 8.2 ms/step against a
~2.2-2.9 ms causal-analytic floor — a ~3x "small-grid tax" attributed to
per-cell VPU softmax + DMA that the tiny sequential grid cannot amortize.
This probe bounds that claim cheaply: it slope-times ONE layer's flash
fwd+bwd at the bench shape (B8 H8 T1024 D128) and at the longcontext
shape (B1 H8 T4096 D128, an 8x longer K loop per cell) and prints each
against its own analytic floor. If the tax ratio falls materially at
T=4096, the gap is T=1024-specific (amortization), not a kernel-schedule
defect — and the perf.md sentence "only a materially different schedule
could attack it" gets scoped to short sequences.

``--sweep`` (PR 12) replaces the fixed two-point comparison with a drive
of the tunable flash schedule surface itself: every viable
(q_block, k_block, heads_per_block) candidate from
``pallas_attention.flash_candidates`` is slope-timed against the 512/512
default baseline, so the short-sequence gap is attacked by search instead
of by two hand-picked points. ``tools/perf_lab.py tune`` builds on exactly
this sweep and applies the adoption discipline (>5% measured win -> a
TuningDB entry; anything else -> a recorded negative). ``--list`` prints
the candidate space without measuring (inspectable on any backend).

Floor model: 8 MXU passes/layer (2 fwd + 6 bwd, the FA-2 recipe — the
QK^T replay runs in BOTH backward kernels), each 2*B*H*(T^2/2)*D FLOPs
causal, at the chip's measured 190 TF/s big-matmul rate.

Usage: python tools/probe_fa_gap.py [--sweep|--list] [--iters N]
           [--reps N] [B,H,T,D ...]
"""
import json
import sys

sys.path.insert(0, ".")
import numpy as np  # noqa: E402

MEASURED_PEAK_TFS = 190.0  # tools/perf_lab.py big-matmul rate
CONFIGS = ((8, 8, 1024, 128),   # bench transformer layer (r5: 8.2ms/8 layers)
           (1, 8, 4096, 128))   # longcontext layer


def floor_ms(b, h, t, d):
    flops = 8 * 2 * b * h * (t * t / 2) * d
    return flops / (MEASURED_PEAK_TFS * 1e12) * 1e3


def measure(b, h, t, d, iters=8, reps=3, q_block=512, k_block=512,
            heads_per_block="auto"):
    """One layer's flash fwd+bwd ms at ONE schedule point via the shared
    chained-window slope (profiler.chained_slope_ms — the same instrument
    pallas_matmul's autotune uses; the q-scaling chain keeps XLA from
    hoisting or DCE'ing the loop-invariant kernel calls). The schedule
    knobs are passed EXPLICITLY so the probe always measures the point it
    names, never whatever the tuning DB currently resolves."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from paddle_tpu.ops.pallas_attention import flash_attention
    from paddle_tpu.profiler import chained_slope_ms

    rng = np.random.RandomState(0)
    q0 = jnp.asarray(rng.randn(b, t, h, d), jnp.bfloat16)

    def step(q):
        out, vjp = jax.vjp(
            lambda q: flash_attention(q, q, q, True, None, q_block, k_block,
                                      heads_per_block), q)
        (dq,) = vjp(out)
        return dq

    def window(n):
        @jax.jit
        def run(q):
            def body(_, carry):
                q, s = carry
                dq = step(q)
                s = dq[0, 0, 0, 0].astype(jnp.float32)
                q = q * (1.0 + s * 1e-30).astype(q.dtype)
                return q, s
            _, s = lax.fori_loop(0, n, body, (q, jnp.float32(0.0)))
            return s
        return run

    return chained_slope_ms(window, iters=iters, reps=reps, args=(q0,))


def sweep(b, h, t, d, iters=8, reps=3, candidates=None):
    """Drive the flash schedule surface: slope-time every candidate config
    and return ``(baseline_ms, rows)`` — baseline is the 512/512/auto
    default, rows carry each candidate's config, ms, and ratio vs the
    baseline (sorted fastest first). The kernel-level instrument
    `perf_lab.py tune` applies the adoption discipline on top of."""
    from paddle_tpu.ops.pallas_attention import flash_candidates

    cands = (flash_candidates(t, h, d) if candidates is None
             else list(candidates))
    base_ms = measure(b, h, t, d, iters=iters, reps=reps)
    rows = []
    for cfg in cands:
        ms = measure(b, h, t, d, iters=iters, reps=reps, **cfg)
        rows.append({"config": dict(cfg), "fwd_bwd_ms": round(ms, 3),
                     "vs_default": round(ms / base_ms, 3)})
    rows.sort(key=lambda r: r["fwd_bwd_ms"])
    return base_ms, rows


def _parse_args(argv):
    opts = {"sweep": False, "list": False, "iters": 8, "reps": 3}
    configs = []
    it = iter(argv)
    for a in it:
        if a == "--sweep":
            opts["sweep"] = True
        elif a == "--list":
            opts["list"] = True
        elif a == "--iters":
            opts["iters"] = int(next(it))
        elif a == "--reps":
            opts["reps"] = int(next(it))
        else:
            configs.append(tuple(int(x) for x in a.split(",")))
    return opts, (configs or list(CONFIGS))


if __name__ == "__main__":
    opts, configs = _parse_args(sys.argv[1:])
    if opts["list"]:
        from paddle_tpu.ops.pallas_attention import flash_candidates

        for (b, h, t, d) in configs:
            print(json.dumps({
                "config": {"B": b, "H": h, "T": t, "D": d},
                "candidates": flash_candidates(t, h, d),
            }), flush=True)
        sys.exit(0)
    for (b, h, t, d) in configs:
        fl = floor_ms(b, h, t, d)
        if opts["sweep"]:
            base_ms, rows = sweep(b, h, t, d, iters=opts["iters"],
                                  reps=opts["reps"])
            best = rows[0] if rows else None
            print(json.dumps({
                "config": {"B": b, "H": h, "T": t, "D": d},
                "default_ms": round(base_ms, 3),
                "analytic_floor_ms": round(fl, 3),
                "default_tax_ratio": round(base_ms / fl, 2),
                "best": best,
                "rows": rows,
            }), flush=True)
            continue
        ms = measure(b, h, t, d, iters=opts["iters"], reps=opts["reps"])
        print(json.dumps({
            "config": {"B": b, "H": h, "T": t, "D": d},
            "fwd_bwd_ms": round(ms, 3),
            "analytic_floor_ms": round(fl, 3),
            "tax_ratio": round(ms / fl, 2),
            "grid_cells_per_bh": t // 512 if t >= 512 else 1,
        }), flush=True)

"""Convert a dumped profile to Chrome-trace JSON (<- tools/timeline.py:114,
which converts profiler.proto to chrome://tracing format).

Input: the JSON written by ``paddle_tpu.profiler.dump_profile`` (host
events). Device-side traces are produced directly by jax.profiler in
TensorBoard/perfetto format — this tool covers the host-event timeline the
reference's CPU events occupied.

Usage::

    python tools/timeline.py --profile_path prof.json --timeline_path out.json
    # open chrome://tracing (or ui.perfetto.dev) and load out.json
"""
from __future__ import annotations

import argparse
import json


class _ChromeTraceFormatter:
    """<- tools/timeline.py _ChromeTraceFormatter: same event schema."""

    def __init__(self):
        self._events = []
        self._metadata = []

    def emit_pid(self, name, pid):
        self._metadata.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name},
        })

    def emit_region(self, timestamp_us, duration_us, pid, tid, category, name,
                    args=None):
        self._events.append({
            "ph": "X", "cat": category, "name": name, "pid": pid, "tid": tid,
            "ts": timestamp_us, "dur": duration_us, "args": args or {},
        })

    def emit_counter(self, timestamp_us, pid, name, values):
        self._events.append({
            "ph": "C", "cat": "mem", "name": name, "pid": pid, "tid": 0,
            "ts": timestamp_us, "args": values,
        })

    def format_to_string(self, pretty=False):
        trace = {"traceEvents": self._metadata + self._events}
        return json.dumps(trace, indent=4 if pretty else None,
                          separators=None if pretty else (",", ":"))


def to_chrome_trace(profile: dict, pretty=False, obs_trace: dict = None,
                    goodput: dict = None, mem: dict = None) -> str:
    """``obs_trace`` (an ``obs.Tracer.to_chrome_trace()`` dict or a loaded
    dump file) merges into the same timeline: profiler host events land on
    pid 0, obs spans on pid 1. When the obs dump carries its absolute
    monotonic base (``t0_monotonic``, written by ``Tracer.to_chrome_trace``)
    the obs lane is re-based onto the profiler's zero so the two planes are
    genuinely time-aligned (both clocks are CLOCK_MONOTONIC on Linux — see
    profiler.RecordEvent re-emission); without it the obs lane keeps its
    own zero (distinguishable, alignment best-effort).

    ``goodput`` (a ``GoodputAccountant.dump_intervals()`` dump) adds the
    accountant's per-category lanes on pid 2 — one tid per taxonomy
    category, so the category owning a regression is visible as a lane in
    the same view as the spans it classifies (docs/design.md §23).

    ``mem`` (a ``MemoryLedger.dump_intervals()`` dump) adds the memory
    plane on pid 3 — one tid per ledger component, each allocation's
    residency as a region (bytes in args), plus a ``hbm total`` counter
    series from the high-water ring, so an allocation spike lines up
    against the span that caused it (docs/design.md §28)."""
    f = _ChromeTraceFormatter()
    f.emit_pid("host", 0)
    events = profile.get("events", [])
    t0 = min((e["start"] for e in events), default=0.0)
    obs_events = []
    obs_shift_us = 0.0
    if obs_trace:
        obs_events = [e for e in obs_trace.get("traceEvents", [])
                      if e.get("ph") == "X"]
        if obs_events:
            f.emit_pid("obs spans", 1)
            obs_t0 = obs_trace.get("t0_monotonic")
            if obs_t0 is not None and events:
                obs_shift_us = (float(obs_t0) - t0) * 1e6
    for e in events:
        f.emit_region(
            timestamp_us=(e["start"] - t0) * 1e6,
            duration_us=e["dur"] * 1e6,
            pid=0,
            tid=e.get("tid", 0),
            category="host",
            name=e["name"],
        )
    for e in obs_events:
        f.emit_region(
            timestamp_us=e["ts"] + obs_shift_us, duration_us=e["dur"],
            pid=1, tid=e.get("tid", 0), category=e.get("cat", "obs"),
            name=e["name"], args=e.get("args"))
    if goodput:
        ivs = goodput.get("intervals") or []
        if ivs:
            f.emit_pid("goodput categories", 2)
            # intervals carry absolute monotonic t0s: rebase onto the
            # profiler's zero when host events exist, else their own
            base = t0 if events else min(iv["t0"] for iv in ivs)
            tids = {}  # category -> stable lane id, first-seen order
            for iv in ivs:
                cat = iv.get("category", "?")
                tid = tids.setdefault(cat, len(tids))
                f.emit_region(
                    timestamp_us=(iv["t0"] - base) * 1e6,
                    duration_us=iv["dur"] * 1e6,
                    pid=2, tid=tid, category="goodput", name=cat,
                    args={"good": bool(iv.get("good"))})
    if mem:
        ivs = mem.get("intervals") or []
        hist = mem.get("high_water_history") or []
        if ivs or hist:
            f.emit_pid("memory components", 3)
            # same rebase rule as the goodput lane: ledger t0s are
            # absolute monotonic stamps
            stamps = ([iv["t0"] for iv in ivs]
                      + [float(h[0]) for h in hist])
            base = t0 if events else min(stamps)
            tids = {}  # component -> stable lane id, first-seen order
            for iv in ivs:
                comp = iv.get("component", "?")
                tid = tids.setdefault(comp, len(tids))
                f.emit_region(
                    timestamp_us=(iv["t0"] - base) * 1e6,
                    duration_us=iv["dur"] * 1e6,
                    pid=3, tid=tid, category="mem",
                    name=f"{comp}:{iv.get('label', '')}",
                    args={"bytes": int(iv.get("bytes", 0)),
                          "device": iv.get("device", "device"),
                          "live": bool(iv.get("live"))})
            for h in hist:
                f.emit_counter(
                    timestamp_us=(float(h[0]) - base) * 1e6, pid=3,
                    name="hbm total", values={"bytes": int(h[1])})
    return f.format_to_string(pretty)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--profile_path", type=str, required=True,
                        help="profile JSON from paddle_tpu.profiler.dump_profile")
    parser.add_argument("--timeline_path", type=str, required=True,
                        help="output Chrome-trace JSON")
    parser.add_argument("--obs_path", type=str, default=None,
                        help="optional obs tracer Chrome-trace dump "
                             "(obs.get_tracer().dump(...)) to merge in")
    parser.add_argument("--goodput_path", type=str, default=None,
                        help="optional goodput interval dump "
                             "(obs.get_accountant().dump_intervals(...)) "
                             "— adds one lane per taxonomy category")
    parser.add_argument("--mem_path", type=str, default=None,
                        help="optional memory-ledger interval dump "
                             "(obs.mem.get_ledger().dump_intervals()) "
                             "— adds one lane per ledger component")
    args = parser.parse_args()
    with open(args.profile_path) as f:
        profile = json.load(f)
    obs_trace = None
    if args.obs_path:
        with open(args.obs_path) as f:
            obs_trace = json.load(f)
    goodput = None
    if args.goodput_path:
        with open(args.goodput_path) as f:
            goodput = json.load(f)
    mem = None
    if args.mem_path:
        with open(args.mem_path) as f:
            mem = json.load(f)
    with open(args.timeline_path, "w") as f:
        f.write(to_chrome_trace(profile, pretty=True, obs_trace=obs_trace,
                                goodput=goodput, mem=mem))
    print("timeline written to", args.timeline_path)


if __name__ == "__main__":
    main()

"""Measure the IR-autodiff recompute tax: compiled-FLOP ratio of a
fwd+bwd+update training step vs the forward-only program.

core/registry.py's generic_grad_impl computes every grad op as jax.vjp over
a re-run of the forward kernel inside the same traced block, relying on
XLA CSE to fold the recomputation into the original forward (<- the
reference instead saves forward vars for grad ops, backward.py:280). This
tool makes that reliance a measured number: the analytic ideal for
matmul-dominated models is ~3x forward (fwd + dX + dW), so a healthy
compiled ratio is ~<=3.5; a regression toward ~5-6x means CSE stopped
folding the replays.

Usage: python tools/grad_flops.py [--model transformer|mlp]
(CPU or TPU; FLOP counts come from XLA cost analysis, not wall clock.)
Also imported by tests/test_autodiff.py::test_grad_flops_ratio_bounded.
"""
import argparse


def build_programs(model="transformer"):
    import paddle_tpu as fluid

    if model == "transformer":
        from paddle_tpu.models.transformer import transformer_lm

        d, layers, heads, t, bs, vocab = 256, 2, 2, 128, 2, 1000
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            tok = fluid.layers.data("tokens", shape=[t], dtype="int64")
            lbl = fluid.layers.data("labels", shape=[t], dtype="int64")
            _, loss = transformer_lm(tok, lbl, vocab_size=vocab, max_len=t,
                                     d_model=d, n_heads=heads,
                                     n_layers=layers, d_ff=4 * d)
            fwd = main.clone(for_test=False)
            fluid.optimizer.Adam(1e-3).minimize(loss, startup)
        feeds = {"tokens": ((bs, t), "int64", vocab),
                 "labels": ((bs, t), "int64", vocab)}
    elif model == "mlp":
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[256], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, size=512, act="relu")
            h = fluid.layers.fc(h, size=512, act="relu")
            p = fluid.layers.fc(h, size=10, act="softmax")
            loss = fluid.layers.mean(fluid.layers.cross_entropy(p, y))
            fwd = main.clone(for_test=False)
            fluid.optimizer.SGD(0.1).minimize(loss, startup)
        feeds = {"x": ((64, 256), "float32", None), "y": ((64, 1), "int64", 10)}
    else:
        raise SystemExit(f"unknown model {model}")
    return main, fwd, startup, loss, feeds


def compiled_flops(program, startup, feeds, fetch_names, amp=False):
    import jax
    import numpy as np

    from paddle_tpu.core.executor import build_step_fn

    feed_names = tuple(sorted(feeds))
    step, readonly, donated, state_out = build_step_fn(
        program, 0, feed_names, fetch_names, amp=amp)

    rng = np.random.RandomState(0)
    cpu = jax.devices("cpu")[0]

    def mk(shape, dtype, hi):
        if dtype == "int64":
            return jax.device_put(
                rng.randint(0, hi, shape).astype("int32"), cpu)
        return jax.device_put(rng.randn(*shape).astype(dtype), cpu)

    feed_vals = {k: mk(*feeds[k]) for k in feed_names}

    # state comes from the startup program run on CPU
    import paddle_tpu as fluid

    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope, seed=3)
    ro = {n: jax.device_put(scope.get(n), cpu) for n in readonly}
    do = {n: jax.device_put(scope.get(n), cpu) for n in donated}
    key = jax.random.PRNGKey(0)
    with jax.default_device(cpu):
        lowered = jax.jit(step).lower(feed_vals, ro, do, key)
        cost = lowered.compile().cost_analysis()
    return float(cost.get("flops", 0.0))


def measure(model="transformer", amp=False):
    main, fwd, startup, loss, feeds = build_programs(model)
    f_fwd = compiled_flops(fwd, startup, feeds, [loss.name], amp=amp)
    f_train = compiled_flops(main, startup, feeds, [loss.name], amp=amp)
    ratio = f_train / f_fwd if f_fwd else float("nan")
    return f_fwd, f_train, ratio


if __name__ == "__main__":
    import sys

    sys.path.insert(0, ".")
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="transformer")
    ap.add_argument("--amp", action="store_true")
    args = ap.parse_args()
    f, t, r = measure(args.model, args.amp)
    print(f"{args.model}: forward {f/1e9:.3f} GFLOP  train-step {t/1e9:.3f} "
          f"GFLOP  ratio {r:.2f} (ideal ~3, healthy <=3.6)")

"""Decompose ResNet-50 step time on-chip: where exactly do the BN
milliseconds live (fwd stats/normalize vs backward reductions)?

Variants (pure-JAX NHWC, bf16 activations, momentum update, one-pass BN
stats — the bench-equivalent config from docs/perf.md):

  std        : training BN (batch stats, full backward)
  nostatgrad : batch stats under stop_gradient — BN backward collapses to
               dx = a * dy (no mean(dy)/mean(dy*xhat) reduction terms)
  affine     : no stats at all — y = scale*x + bias (BN removed, affine kept)

For each, measures full train-step AND forward-only (loss) time with the
slope method. The differences isolate:
  fwd BN cost        = fwd(std) - fwd(affine)
  bwd BN cost        = [step(std)-fwd(std)] - [step(affine)-fwd(affine)]
  bwd reduction cost = step(std) - step(nostatgrad)

Usage: python tools/probe_resnet_split.py [--batch 128]
"""
import argparse
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from tools.perf_lab import init_params  # noqa: E402

BATCH = 128
IMAGE = 224
CLASSES = 1000


DOT_1X1 = False
NO_DW = False      # stop_gradient on conv weights: isolates the dX chain
NO_DX = False      # stop_gradient on conv inputs: isolates dW cost


def _conv(x, w, stride):
    if NO_DW:
        w = jax.lax.stop_gradient(w)
    if NO_DX:
        x = jax.lax.stop_gradient(x)
    if DOT_1X1 and w.shape[0] == 1 and w.shape[1] == 1:
        # 1x1 conv as an explicit matmul over [N*H*W, K]: XLA's conv
        # backward emitter runs dX/dW far below matmul speed; as dots the
        # whole bwd is MXU-shaped
        if stride != 1:
            x = x[:, ::stride, ::stride, :]
        n, h, wd, k = x.shape
        y = jax.lax.dot_general(
            x.reshape(n * h * wd, k), w.astype(jnp.bfloat16)[0, 0],
            (((1,), (0,)), ((), ())))
        return y.reshape(n, h, wd, -1)
    pads = [(w.shape[0] // 2, w.shape[0] // 2)] * 2
    return jax.lax.conv_general_dilated(
        x, w.astype(jnp.bfloat16), (stride, stride), pads,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn(x, p, mode):
    if mode == "bf16affine":
        return x * p["scale"].astype(x.dtype) + p["bias"].astype(x.dtype)
    xf = x.astype(jnp.float32)
    if mode == "affine":
        y = xf * p["scale"] + p["bias"]
        return y.astype(x.dtype)
    mean = jnp.mean(xf, axis=(0, 1, 2))
    var = jnp.maximum(jnp.mean(xf * xf, axis=(0, 1, 2)) - mean * mean, 0.0)
    if mode == "nostatgrad":
        mean = jax.lax.stop_gradient(mean)
        var = jax.lax.stop_gradient(var)
    inv = jax.lax.rsqrt(var + 1e-5)
    if mode == "bf16apply":
        # stats reductions stay f32; the folded per-channel affine is cast
        # to bf16 and the normalize applies in bf16 arithmetic, so the
        # whole backward chain between convs flows bf16 (half the bytes)
        a = (inv * p["scale"]).astype(x.dtype)
        b = (p["bias"] - mean * inv * p["scale"]).astype(x.dtype)
        return x * a + b
    y = (xf - mean) * inv * p["scale"] + p["bias"]
    return y.astype(x.dtype)


def forward(params, blocks, img, label, mode):
    fused = mode in ("fusedblocks", "hybridblocks")
    bn_mode = "std"
    x = img.astype(jnp.bfloat16)
    x = jnp.transpose(x, (0, 2, 3, 1))
    x = _bn(_conv(x, params["stem_w"], 2), params["stem_bn"], bn_mode if fused else mode)
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
        [(0, 0), (1, 1), (1, 1), (0, 0)])
    block_fn = None
    if fused:
        from paddle_tpu.ops.fused_resnet import (bottleneck_fused,
                                                 bottleneck_hybrid)
        block_fn = (bottleneck_hybrid if mode == "hybridblocks"
                    else bottleneck_fused)

    def xla_block(x, name, stride, has_sc, m):
        short = x
        if has_sc:
            short = _bn(_conv(x, params[name + "_sc_w"], stride),
                        params[name + "_sc_bn"], m)
        y = jax.nn.relu(_bn(_conv(x, params[name + "_c1_w"], stride),
                            params[name + "_c1_bn"], m))
        y = jax.nn.relu(_bn(_conv(y, params[name + "_c2_w"], 1),
                            params[name + "_c2_bn"], m))
        y = _bn(_conv(y, params[name + "_c3_w"], 1),
                params[name + "_c3_bn"], m)
        return jax.nn.relu(short + y)

    for name, stride, has_sc in blocks:
        if fused and not has_sc and stride == 1:
            x, _stats = block_fn(
                x, params[name + "_c1_w"][0, 0],
                params[name + "_c2_w"], params[name + "_c3_w"][0, 0],
                params[name + "_c1_bn"]["scale"],
                params[name + "_c1_bn"]["bias"],
                params[name + "_c2_bn"]["scale"],
                params[name + "_c2_bn"]["bias"],
                params[name + "_c3_bn"]["scale"],
                params[name + "_c3_bn"]["bias"])
        else:
            x = xla_block(x, name, stride, has_sc,
                          bn_mode if fused else mode)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    logits = x.astype(jnp.bfloat16) @ params["fc_w"].astype(jnp.bfloat16)
    logits = logits.astype(jnp.float32) + params["fc_b"]
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, label, axis=1))


def slope(fn, sync, n1=10, n2=50):
    for _ in range(5):
        fn()
    sync()
    def win(n):
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        sync()
        return time.perf_counter() - t0
    win(n1)
    t1, t2 = win(n1), win(n2)
    dt = (t2 - t1) / (n2 - n1)
    return dt if dt > 0 else t2 / n2


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=BATCH)
    ap.add_argument("--modes", default="std,nostatgrad,affine")
    ap.add_argument("--dot_1x1", action="store_true")
    ap.add_argument("--no_dw", action="store_true")
    ap.add_argument("--no_dx", action="store_true")
    args = ap.parse_args()
    global DOT_1X1, NO_DW, NO_DX
    DOT_1X1 = args.dot_1x1
    NO_DW = args.no_dw
    NO_DX = args.no_dx
    b = args.batch
    rng = np.random.RandomState(0)
    params, blocks = init_params(rng, "nhwc")
    dev = jax.devices()[0]
    params = jax.device_put(params, dev)
    img = jax.device_put(rng.randn(b, 3, IMAGE, IMAGE).astype(np.float32), dev)
    label = jax.device_put(rng.randint(0, CLASSES, (b, 1)), dev)

    for mode in args.modes.split(","):
        velo = jax.tree.map(jnp.zeros_like, params)
        p = jax.device_put(params, dev)

        @jax.jit
        def step(params, velo, img, label, _m=mode):
            loss, grads = jax.value_and_grad(
                lambda q: forward(q, blocks, img, label, _m))(params)
            velo = jax.tree.map(lambda v, g: 0.9 * v + g, velo, grads)
            params = jax.tree.map(lambda p, v: p - 0.1 * v, params, velo)
            return params, velo, loss

        @jax.jit
        def fwd(params, img, label, _m=mode):
            return forward(params, blocks, img, label, _m)

        state = {"p": p, "v": velo, "l": None}

        def run_step():
            state["p"], state["v"], state["l"] = step(
                state["p"], state["v"], img, label)

        t_step = slope(run_step, lambda: float(state["l"])) * 1e3

        lbox = {"l": None}

        def run_fwd():
            lbox["l"] = fwd(state["p"], img, label)

        t_fwd = slope(run_fwd, lambda: float(lbox["l"])) * 1e3
        print(f"{mode:10s}: step {t_step:6.2f} ms ({b/t_step*1e3:7.1f} img/s)"
              f"   fwd-only {t_fwd:6.2f} ms", flush=True)


if __name__ == "__main__":
    main()

"""On-chip host-IO overlap probes (VERDICT r3 items 6+7).

(a) input pipeline: train-step time fed per-step from the csrc
    RecordIO->shuffle->batch pipeline vs device-resident data — the
    double-buffer-reader overlap question, measured on the real chip.
(b) host-table CTR: HostTableSession.run (serial gather -> step ->
    update) vs run_prefetched (gather/update overlap the device step).

Slope-timed; numbers land in docs/perf.md. Run: python tools/probe_host_io.py
"""
import json
import sys
import tempfile
import time

sys.path.insert(0, ".")
import numpy as np  # noqa: E402


def bench_input_pipeline():
    import jax

    import paddle_tpu as fluid
    from paddle_tpu import recordio
    from paddle_tpu.profiler import slope_time
    from paddle_tpu.reader.native import NativeBatchLoader

    # LeNet-ish mnist workload: a realistic decode+feed payload without the
    # tunnel-pathological 77 MB/step of ResNet bs128 (measured separately)
    B, C, H, W = 256, 1, 28, 28
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[C, H, W], dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        from paddle_tpu.models import lenet5
        pred, loss, acc = lenet5(img, label)
        fluid.optimizer.Adam(1e-3).minimize(loss, startup)
    place = fluid.default_place()
    exe = fluid.Executor(place, amp=True)
    scope = fluid.Scope()
    exe.run(startup, scope=scope, seed=1)

    rng = np.random.RandomState(0)
    dev = place.jax_device()
    x_dev = jax.device_put(rng.rand(B, C, H, W).astype("float32"), dev)
    y_dev = jax.device_put(rng.randint(0, 10, (B, 1)).astype("int32"), dev)

    t_res = slope_time(
        lambda: exe.run(main, feed={"img": x_dev, "label": y_dev},
                        fetch_list=[], scope=scope),
        lambda: exe.run(main, feed={"img": x_dev, "label": y_dev},
                        fetch_list=[loss], scope=scope),
        warmup=3, iters=40, prime=True)

    # write a RecordIO shard of image+label records, stream through csrc
    with tempfile.TemporaryDirectory() as d:
        rec = np.empty(C * H * W + 1, "float32")
        path = d + "/data.rio"
        w = recordio.Writer(path)
        for i in range(B * 8):
            rec[:-1] = rng.rand(C * H * W)
            rec[-1] = i % 10
            w.write(rec.tobytes())
        w.close()

        def run_pipeline_epoch(n_fetch):
            loader = NativeBatchLoader([path], record_shape=[C * H * W + 1],
                                       batch_size=B, shuffle_buf=1024,
                                       capacity=8, drop_last=True)
            t0 = time.perf_counter()
            steps = 0
            last = None
            for batch in loader:
                feed = {"img": batch[:, :-1].reshape(B, C, H, W),
                        "label": batch[:, -1:].astype("int64")}
                last = exe.run(main, feed=feed,
                               fetch_list=[loss] if steps == n_fetch else [],
                               scope=scope)
                steps += 1
            np.asarray(last[0]) if last and last[0] is not None else None
            return (time.perf_counter() - t0) / steps

        run_pipeline_epoch(7)  # warmup/compile for host-fed shapes
        t_pipe = min(run_pipeline_epoch(7) for _ in range(3))
    print(json.dumps({
        "probe": "input_pipeline_lenet_b256",
        "device_resident_ms": round(t_res * 1e3, 3),
        "csrc_pipeline_fed_ms": round(t_pipe * 1e3, 3),
        "overhead_pct": round((t_pipe / t_res - 1) * 100, 1)}))


def bench_host_table():
    import paddle_tpu as fluid
    from paddle_tpu.host_table import (HostEmbeddingTable, HostTableSession,
                                       host_embedding)

    V, E, S, B = 2_000_000, 32, 16, 1024
    table = HostEmbeddingTable("probe", rows=V, dim=E, lr=0.1)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        dense = fluid.layers.data("dense", shape=[16], dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="float32")
        emb = host_embedding(table, batch_slots=S, program=main)
        flat = fluid.layers.reshape(emb, [0, S * E])
        x = fluid.layers.concat([flat, dense], axis=1)
        x = fluid.layers.fc(x, size=256, act="relu")
        x = fluid.layers.fc(x, size=256, act="relu")
        logit = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(logit, label))
        fluid.optimizer.Adam(1e-3).minimize(loss, startup)
    place = fluid.default_place()
    exe = fluid.Executor(place, amp=True)
    scope = fluid.Scope()
    exe.run(startup, scope=scope, seed=2)
    sess = HostTableSession(exe, main, [table], scope=scope)
    rng = np.random.RandomState(3)

    def make_batches(n):
        out = []
        for _ in range(n):
            ids = rng.randint(0, V, (B, S)).astype("int64")
            dense_b = rng.randn(B, 16).astype("float32")
            out.append(({"dense": dense_b,
                         "label": (dense_b[:, :1] > 0).astype("float32")},
                        {"probe": ids}))
        return out

    warm = make_batches(3)
    for feed, ids in warm:
        sess.run(feed=feed, ids=ids, fetch_list=[loss.name])

    n = 30
    batches = make_batches(n)
    t0 = time.perf_counter()
    for feed, ids in batches:
        sess.run(feed=feed, ids=ids, fetch_list=[loss.name])
    t_serial = (time.perf_counter() - t0) / n

    batches = make_batches(n)
    t0 = time.perf_counter()
    for _ in sess.run_prefetched(batches, fetch_list=[loss.name]):
        pass
    t_overlap = (time.perf_counter() - t0) / n
    print(json.dumps({
        "probe": "host_table_ctr_b1024_s16_v2m",
        "serial_ms": round(t_serial * 1e3, 3),
        "prefetched_ms": round(t_overlap * 1e3, 3),
        "overlap_gain_pct": round((1 - t_overlap / t_serial) * 100, 1)}))


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    if which in ("both", "input"):
        bench_input_pipeline()
    if which in ("both", "table"):
        bench_host_table()

"""Parse a jax.profiler xplane.pb into a per-op device-time table.

The r3 ResNet roofline was built from an ad-hoc version of this; now a
tool: aggregates device self-time by operation type (and top ops by name),
excluding IDLE — on a tunneled chip most wall-clock is inter-step idle, so
only relative device time is meaningful.
Usage: python tools/trace_ops.py <xplane.pb> [top_n]
"""
import json
import sys


def load(pb):
    from xprof.convert import raw_to_tool_data as rtd

    data, _ = rtd.xspace_to_tool_data([pb], "framework_op_stats", {})
    obj = json.loads(data) if isinstance(data, (str, bytes)) else data
    table = obj[0]
    cols = [c["id"] for c in table["cols"]]
    rows = [[cell["v"] for cell in r["c"]] for r in table["rows"]]
    return cols, rows


def main(pb, top_n=25):
    cols, rows = load(pb)
    i_dev = cols.index("host_or_device")
    i_type = cols.index("type")
    i_name = cols.index("operation")
    i_self = cols.index("total_self_time")
    dev_rows = [r for r in rows if r[i_dev] == "Device" and r[i_type] != "IDLE"]
    total = sum(r[i_self] for r in dev_rows)
    by_type = {}
    for r in dev_rows:
        by_type[r[i_type]] = by_type.get(r[i_type], 0.0) + r[i_self]
    print(f"device busy time: {total/1e3:.2f} ms (trace total, all steps)")
    print("\n-- by op type --")
    for t, us in sorted(by_type.items(), key=lambda kv: -kv[1])[:top_n]:
        print(f"{us/1e3:9.2f} ms  {us/total*100:5.1f}%  {t}")
    print("\n-- top ops by name --")
    for r in sorted(dev_rows, key=lambda r: -r[i_self])[:top_n]:
        print(f"{r[i_self]/1e3:9.2f} ms  {r[i_self]/total*100:5.1f}%  "
              f"{r[i_type]:20s} {str(r[i_name])[:80]}")


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]) if len(sys.argv) > 2 else 25)

"""Local-SGD quality study: final loss vs sync SGD at EQUAL step counts.

The async_mode docstring (parallel/parallel_executor.py BuildStrategy)
claims local SGD is "the sound collective version" of the reference's
async pserver trade (listen_and_serv_op.cc:166 RunAsyncLoop); this tool
quantifies the trade the claim glosses over: how much final-loss quality
each sync period K costs on the LM workload at the same number of steps.

    python tools/local_sgd_study.py [--steps 120] [--dp 8]

Run on the virtual CPU mesh (deterministic); the numbers feed
docs/perf.md's local-SGD table and the data-driven default of
BuildStrategy.local_sgd_steps.
"""
import argparse
import os
import sys

sys.path.insert(0, ".")

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def run_variant(local_sgd_steps, steps, dp, seed=5):
    import jax

    jax.config.update("jax_default_device", jax.devices("cpu")[0])
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.models.transformer import transformer_lm
    from paddle_tpu.parallel import BuildStrategy, ParallelExecutor, make_mesh

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[32], dtype="int64")
        lbl = fluid.layers.data("lbl", shape=[32], dtype="int64")
        _, loss = transformer_lm(ids, lbl, vocab_size=128, max_len=32,
                                 d_model=32, n_heads=2, n_layers=2, d_ff=64)
        fluid.optimizer.Adam(2e-3).minimize(loss, startup)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope, seed=seed)
    mesh = make_mesh({"dp": dp}, devices=jax.devices("cpu")[:dp])
    bs = BuildStrategy()
    if local_sgd_steps is not None:
        bs.async_mode = True
        bs.local_sgd_steps = local_sgd_steps
    pe = ParallelExecutor(use_tpu=False, main_program=main, scope=scope,
                          mesh=mesh, build_strategy=bs)
    rng = np.random.RandomState(0)
    # learnable synthetic grammar: next token = (tok * 3 + 1) % vocab
    def batch(n=32):
        start = rng.randint(0, 128, (n, 1))
        seq = [start]
        for _ in range(32):
            seq.append((seq[-1] * 3 + 1) % 128)
        arr = np.concatenate(seq, axis=1)
        return arr[:, :32].astype("int64"), arr[:, 1:33].astype("int64")

    last = []
    for i in range(steps):
        x, y = batch()
        (lv,) = pe.run(fetch_list=[loss.name], feed={"ids": x, "lbl": y})
        if i >= steps - 10:
            last.append(float(lv))
    return sum(last) / len(last)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=120)
    ap.add_argument("--dp", type=int, default=8)
    ap.add_argument("--variant", default=None,
                    help="internal: run one variant in-process")
    args = ap.parse_args()
    if args.variant is not None:
        k = None if args.variant == "sync" else int(args.variant)
        print(f"FINAL {run_variant(k, args.steps, args.dp):.4f}", flush=True)
        return
    # one subprocess per variant: XLA's in-process CPU collectives deadlock
    # when a second executor generation starts in the same process
    import subprocess

    rows = [("sync", "sync"), ("K=1", "1"), ("K=4", "4"), ("K=16", "16")]
    for name, v in rows:
        out = subprocess.run(
            [sys.executable, __file__, "--variant", v,
             "--steps", str(args.steps), "--dp", str(args.dp)],
            capture_output=True, text=True, timeout=1200)
        line = [l for l in out.stdout.splitlines() if l.startswith("FINAL")]
        val = line[0].split()[1] if line else f"FAILED\n{out.stdout[-500:]}" \
            f"{out.stderr[-500:]}"
        print(f"{name:6s}: final loss (mean of last 10 steps) {val}",
              flush=True)


if __name__ == "__main__":
    main()

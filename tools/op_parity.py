"""Audit the op inventory against the reference's operator surface
(<- the role tools/print_signatures.py + the op-bench scripts played for
API-stability; SURVEY.md §2b is the source list).

Prints three sections: ops matched 1:1 by name, reference ops covered by a
renamed/redesigned equivalent (with the mapping), and anything uncovered.
Exit code 1 if uncovered ops exist — CI-able.

Usage::  python tools/op_parity.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# SURVEY.md §2b inventory (reference op registration names)
REFERENCE_OPS = """
mul matmul fc bilinear_tensor_product
conv2d conv3d conv2d_transpose conv_shift depthwise_conv2d spp im2sequence
batch_norm layer_norm lrn l1_norm norm clip_by_norm
pool2d pool3d pool2d_with_index maxout unpool
relu sigmoid tanh softmax sequence_softmax prelu exp abs ceil floor round
reciprocal log square softplus softsign sqrt brelu leaky_relu soft_relu elu
relu6 pow stanh hard_shrink thresholded_relu hard_sigmoid swish
elementwise_add elementwise_sub elementwise_mul elementwise_div
elementwise_max elementwise_min elementwise_pow
reduce_sum reduce_mean reduce_max reduce_min reduce_prod cumsum arg_max
arg_min top_k
cross_entropy softmax_with_cross_entropy sigmoid_cross_entropy_with_logits
hinge_loss huber_loss smooth_l1_loss squared_l2_distance log_loss rank_loss
margin_rank_loss modified_huber_loss warpctc nce linear_chain_crf
crf_decoding mean cos_sim
lstm lstmp lstm_unit gru gru_unit row_conv
sequence_concat sequence_conv sequence_erase sequence_expand sequence_pool
sequence_reshape sequence_slice sequence_softmax lod_reset lod_rank_table
lod_tensor_to_array array_to_lod_tensor split_lod_tensor merge_lod_tensor
reorder_lod_tensor_by_rank max_sequence_len shrink_rnn_memory
rnn_memory_helper edit_distance ctc_align chunk_eval beam_search
beam_search_decode
while recurrent conditional_block is_empty less_than less_equal greater_than
greater_equal equal not_equal logical_and logical_or logical_xor logical_not
increment tensor_array_read_write parallel_do
sgd momentum adam adamax adagrad decayed_adagrad adadelta rmsprop ftrl
proximal_gd proximal_adagrad average_accumulates
lookup_table lookup_sparse_table split_selected_rows split_ids merge_ids
one_hot
reshape transpose concat split split_byref expand gather scatter pad crop
slice reverse shape cast assign assign_value fill_constant
fill_constant_batch_size_like fill_zeros_like sum scale minus sign clip
multiplex
uniform_random gaussian_random random_crop dropout
bilinear_interp roi_pool prior_box multiclass_nms box_coder iou_similarity
bipartite_match target_assign mine_hard_examples polygon_box_transform
detection_map
accuracy auc precision_recall mean_iou positive_negative_pair
feed fetch save load save_combine load_combine print
fake_dequantize_max_abs label_smooth
send recv send_barrier fetch_barrier prefetch listen_and_serv gen_nccl_id
nccl_all_reduce channel_send channel_recv channel_create channel_close
select go
""".split()

# reference op -> how this framework provides the capability
REDESIGNED = {
    "fc": "layers.fc -> mul+sum+bias (one fused MXU matmul under XLA)",
    "soft_relu": "softplus functor (same curve family; activations.py)",
    "conditional_block": "cond / row_cond ops (lax.cond lowering)",
    "tensor_array_read_write": "array_read / array_write / array_length ops",
    "parallel_do": "ParallelExecutor mesh sharding (SSA-replication path removed)",
    "rnn_memory_helper": "recurrent op carries memories inside one lax.scan",
    "split_byref": "split op (no by-ref aliasing under functional XLA)",
    "lookup_sparse_table": "sharded embedding tables (transpiler + ctr models)",
    "split_selected_rows": "slice_vars_round_robin + mesh sharding (transpiler)",
    "split_ids": "transpiler id-sharding (distribute_transpiler)",
    "merge_ids": "transpiler id-merge (distribute_transpiler)",
    "feed": "Executor.run feed dict (donated inputs)",
    "fetch": "Executor.run fetch_list",
    "save": "io.save_vars / save_persistables",
    "load": "io.load_vars / load_persistables",
    "save_combine": "io.save_persistables (one dir per save)",
    "load_combine": "io.load_persistables",
    "send": "XLA collectives over ICI (transpiler emits structure only)",
    "recv": "XLA collectives over ICI",
    "send_barrier": "program-order effect of compiled collectives",
    "fetch_barrier": "program-order effect of compiled collectives",
    "prefetch": "sharded-embedding gather (ctr models / transpiler)",
    "listen_and_serv": "pserver plane deleted: sharded params + reduce_scatter",
    "gen_nccl_id": "distributed.init_distributed (jax.distributed bootstrap)",
    "nccl_all_reduce": "GSPMD all-reduce inside the compiled step",
    "channel_send": "concurrency.channel_send (host runtime)",
    "channel_recv": "concurrency.channel_recv",
    "channel_create": "concurrency.make_channel",
    "channel_close": "concurrency.channel_close",
    "select": "concurrency.Select",
    "go": "concurrency.go / Go",
    "bilinear_interp": "bilinear_interp op (also nearest_interp)",
    "smooth_l1_loss": "smooth_l1_loss op",
}

ALIASES = {  # registered under a different name
    "soft_relu": "softplus",
    "conditional_block": "cond",
    "tensor_array_read_write": "array_write",
    "rnn_memory_helper": "recurrent",
    "split_byref": "split",
}


def audit():
    from paddle_tpu.core.registry import registered_ops

    reg = set(registered_ops())
    matched, mapped, missing = [], [], []
    for op in REFERENCE_OPS:
        if op in reg or ALIASES.get(op) in reg:
            matched.append(op)
        elif op in REDESIGNED:
            mapped.append((op, REDESIGNED[op]))
        else:
            missing.append(op)
    extra = sorted(reg - set(REFERENCE_OPS) - set(ALIASES.values()))
    return matched, mapped, missing, extra


def main():
    matched, mapped, missing, extra = audit()
    print(f"matched by name: {len(matched)}")
    print(f"covered by redesign: {len(mapped)}")
    for op, how in mapped:
        print(f"  {op:28s} -> {how}")
    print(f"net-new ops beyond the reference: {len(extra)}")
    print("  " + " ".join(extra))
    if missing:
        print(f"UNCOVERED ({len(missing)}): {' '.join(missing)}")
        return 1
    print("UNCOVERED: none")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Model-level A/B probe: transformer_lm step time vs attention config.

Model-level slope timing is the reliable instrument on the tunneled chip
(spread <0.2 ms/step; kernel microbenches swing 3x with weather —
docs/perf.md). Usage: python tools/probe_tlm.py n_heads [qb kb]
"""
import json
import sys

sys.path.insert(0, ".")
import numpy as np  # noqa: E402

import bench  # noqa: E402
from bench import (PEAK_TFLOPS, TLM_BATCH, TLM_D, TLM_LAYERS, TLM_T,  # noqa: E402
                   TLM_VOCAB, _slope_time)


def run(n_heads, qb=512, kb=512):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tmod
    from paddle_tpu import layers

    # route the model's attention through the requested block config
    orig = layers.flash_attention

    def fa(q, k, v, causal=False, scale=None, q_block=qb, k_block=kb,
           name=None):
        return orig(q, k, v, causal=causal, scale=scale, q_block=qb,
                    k_block=kb, name=name)

    tmod.layers.flash_attention = fa
    try:
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            ids = fluid.layers.data("ids", shape=[TLM_T], dtype="int64")
            labels = fluid.layers.data("labels", shape=[TLM_T], dtype="int64")
            _, loss = tmod.transformer_lm(
                ids, labels, vocab_size=TLM_VOCAB, max_len=TLM_T,
                d_model=TLM_D, n_heads=n_heads, n_layers=TLM_LAYERS,
                d_ff=4 * TLM_D)
            fluid.optimizer.Adam(learning_rate=1e-4).minimize(loss, startup)
    finally:
        tmod.layers.flash_attention = orig
    place = fluid.default_place()
    exe = fluid.Executor(place, amp=True)
    scope = fluid.Scope()
    exe.run(startup, scope=scope, seed=17)
    rng = np.random.RandomState(0)
    dev = place.jax_device()
    X = jax.device_put(
        rng.randint(0, TLM_VOCAB, (TLM_BATCH, TLM_T)).astype("int32"), dev)
    feed = {"ids": X, "labels": X}
    step_time, spread = _slope_time(
        lambda: exe.run(main_prog, feed=feed, fetch_list=[], scope=scope),
        lambda: exe.run(main_prog, feed=feed, fetch_list=[loss], scope=scope),
        warmup=2, iters=10)
    tok_s = TLM_BATCH * TLM_T / step_time
    n_params = TLM_LAYERS * 12 * TLM_D * TLM_D + TLM_VOCAB * TLM_D
    flops_per_token = 6 * n_params + 6 * TLM_LAYERS * TLM_D * TLM_T
    mfu = tok_s * flops_per_token / 1e12 / PEAK_TFLOPS
    print(json.dumps({
        "n_heads": n_heads, "qb": qb, "kb": kb, "tok_s": round(tok_s, 1),
        "mfu": round(mfu, 4), "step_ms": round(step_time * 1e3, 2),
        "spread_ms": round(spread * 1e3, 2)}))


if __name__ == "__main__":
    n_heads = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    qb = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    kb = int(sys.argv[3]) if len(sys.argv) > 3 else 512
    run(n_heads, qb, kb)

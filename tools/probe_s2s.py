"""Seq2seq step-time decomposition probe (slope-timed, on-chip).

Variants: full train step / forward-only / encoder-only train /
decoder-without-attention train — ablation locates the scan-bound cost
the same way tools/perf_lab.py does for ResNet.
Usage: python tools/probe_s2s.py [batch] [len]
"""
import json
import sys

sys.path.insert(0, ".")
import numpy as np  # noqa: E402


def build(batch, length, mode):
    import paddle_tpu as fluid
    from paddle_tpu.models.seq2seq import Seq2SeqAttention

    V, E, H = 30000, 512, 512
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data("src", shape=[length], dtype="int64")
        src_len = fluid.layers.data("src_len", shape=[], dtype="int64")
        trg = fluid.layers.data("trg", shape=[length], dtype="int64")
        trg_len = fluid.layers.data("trg_len", shape=[], dtype="int64")
        trg_next = fluid.layers.data("trg_next", shape=[length], dtype="int64")
        model = Seq2SeqAttention(V, V, embed_dim=E, hidden=H)
        if mode in ("encoder_only", "enc_fwd"):
            enc_out, h0, c0 = model._encode(src, src_len)
            avg = fluid.layers.reduce_mean(enc_out)
        elif mode in ("lstm_fwd", "lstm_train"):
            from paddle_tpu.layers import sequence as seq_layers
            gin = fluid.layers.data("gin", shape=[length, 4 * 512],
                                    dtype="float32")
            enc_out, enc_cell = seq_layers.dynamic_lstm(
                gin, 512, length=src_len,
                param_attr=fluid.ParamAttr("s2s.enc.w"),
                bias_attr=fluid.ParamAttr("s2s.enc.b"))
            avg = fluid.layers.cast(fluid.layers.reduce_mean(enc_out),
                                    "float32")
        elif mode in ("embproj", "embproj_fwd"):
            from paddle_tpu.param_attr import ParamAttr
            src_emb = fluid.layers.embedding(
                src, size=[30000, 512], param_attr=ParamAttr("s2s.src_emb.w"))
            gate_in = fluid.layers.fc(src_emb, size=4 * 512,
                                      num_flatten_dims=2, bias_attr=False,
                                      param_attr=ParamAttr("s2s.src_proj.w"))
            avg = fluid.layers.cast(fluid.layers.reduce_mean(gate_in),
                                    "float32")
        elif mode == "nohead":
            enc_out, h0, c0 = model._encode(src, src_len)
            trg_emb = fluid.layers.embedding(
                trg, size=[30000, 512],
                param_attr=fluid.ParamAttr("s2s.trg_emb.w"))
            from paddle_tpu.layers import sequence as seq_layers
            dec_hidden, _, _ = seq_layers.attention_decoder(
                trg_emb, enc_out, src_len, h0, c0, 512, trg_length=trg_len)
            avg = fluid.layers.reduce_mean(dec_hidden)
        else:
            avg, _ = model.build_train(src, src_len, trg, trg_len, trg_next,
                                       fused_head=(mode == "train_fused"))
        if "fwd" not in mode:
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg, startup)
    return main, startup, avg


def run(batch, length, mode):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.profiler import slope_time

    main, startup, avg = build(batch, length, mode)
    place = fluid.default_place()
    exe = fluid.Executor(place, amp=True)
    scope = fluid.Scope()
    exe.run(startup, scope=scope, seed=11)
    rng = np.random.RandomState(0)
    dev = place.jax_device()
    V = 30000
    feed = {
        "src": jax.device_put(rng.randint(0, V, (batch, length)).astype("int32"), dev),
        "gin": jax.device_put(rng.randn(batch, length, 4 * 512).astype("float32"), dev),
        "src_len": jax.device_put(np.full((batch,), length, "int32"), dev),
        "trg": jax.device_put(rng.randint(0, V, (batch, length)).astype("int32"), dev),
        "trg_len": jax.device_put(np.full((batch,), length, "int32"), dev),
        "trg_next": jax.device_put(rng.randint(0, V, (batch, length)).astype("int32"), dev),
    }
    ts = []
    for _ in range(3):
        ts.append(slope_time(
            lambda: exe.run(main, feed=feed, fetch_list=[], scope=scope),
            lambda: exe.run(main, feed=feed, fetch_list=[avg], scope=scope),
            warmup=3, iters=150, prime=True))
    ts.sort()
    print(json.dumps({"mode": mode, "batch": batch, "len": length,
                      "step_ms": round(ts[1] * 1e3, 3),
                      "spread": round(ts[-1] / ts[0], 2)}), flush=True)


if __name__ == "__main__":
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    length = int(sys.argv[2]) if len(sys.argv) > 2 else 32
    modes = sys.argv[3].split(",") if len(sys.argv) > 3 else [
        "train", "fwd_only", "encoder_only"]
    for m in modes:
        run(batch, length, m)

"""Closed-loop load generator for the serving engine (serving-bench entry).

Drives a ``paddle_tpu.serving.ServingServer`` with N concurrent closed-loop
clients (each sends the next request the moment the previous one returns)
for a fixed duration and reports offered QPS, latency percentiles, rejects,
and the server's own ``stats`` snapshot (batch-fill ratio, compile cache).

Two modes:

* ``--model-dir DIR`` — spawn an in-process server over the exported dir
  (same format ``io.save_inference_model`` writes), bench it, shut down.
* ``--endpoint HOST:PORT`` — bench an already-running server; feed shapes
  then come from ``--shape name=d1,d2`` (repeatable).

Examples::

    JAX_PLATFORMS=cpu python tools/serve_bench.py --model-dir /tmp/model \
        --clients 8 --duration 10 --rows 1 --max-batch-size 16
    python tools/serve_bench.py --endpoint 127.0.0.1:9000 --shape x=4
"""
from __future__ import annotations

import argparse
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from paddle_tpu.serving import ServingClient, ServingRejected, ServingServer  # noqa: E402
from paddle_tpu.serving.stats import _percentile  # noqa: E402


def _client_loop(endpoint, feeds, stop, out):
    lat, done, rejected, errors = [], 0, 0, 0
    with ServingClient(endpoint) as c:
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                c.predict(feeds)
                lat.append(time.monotonic() - t0)
                done += 1
            except ServingRejected:
                rejected += 1
                time.sleep(0.001)  # back off a tick before retrying
            except Exception:
                errors += 1
                break
    out.append((lat, done, rejected, errors))


def bench(endpoint, feeds, clients, duration):
    stop = threading.Event()
    out = []
    threads = [threading.Thread(target=_client_loop,
                                args=(endpoint, feeds, stop, out), daemon=True)
               for _ in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(duration)
    stop.set()
    for t in threads:
        t.join(30)
    elapsed = time.monotonic() - t0
    lats = sorted(l for ls, *_ in out for l in ls)
    done = sum(d for _, d, _, _ in out)
    rejected = sum(r for _, _, r, _ in out)
    errors = sum(e for _, _, _, e in out)
    return {"elapsed_s": elapsed, "requests": done, "rejected": rejected,
            "errors": errors, "qps": done / elapsed if elapsed else 0.0,
            "p50_ms": _percentile(lats, 0.50) * 1e3,
            "p95_ms": _percentile(lats, 0.95) * 1e3,
            "p99_ms": _percentile(lats, 0.99) * 1e3}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model-dir", help="spawn an in-process server over DIR")
    ap.add_argument("--endpoint", help="bench an already-running HOST:PORT")
    ap.add_argument("--shape", action="append", default=[],
                    metavar="name=d1,d2",
                    help="per-request trailing shape of a feed (repeatable; "
                         "required with --endpoint, optional override with "
                         "--model-dir)")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--rows", type=int, default=1,
                    help="rows per request (client-side batch)")
    ap.add_argument("--max-batch-size", type=int, default=16)
    ap.add_argument("--batch-timeout-ms", type=float, default=2.0)
    ap.add_argument("--queue-capacity", type=int, default=256)
    args = ap.parse_args(argv)
    if not args.model_dir and not args.endpoint:
        ap.error("one of --model-dir / --endpoint is required")

    shapes = {}
    for spec in args.shape:
        name, _, dims = spec.partition("=")
        shapes[name] = tuple(int(d) for d in dims.split(",") if d)

    server = None
    try:
        if args.model_dir:
            server = ServingServer(
                args.model_dir, max_batch_size=args.max_batch_size,
                batch_timeout_ms=args.batch_timeout_ms,
                queue_capacity=args.queue_capacity, warmup=True)
            endpoint = server.endpoint
            for n in server.engine.feed_names:
                if n not in shapes:
                    var = server.engine._feed_vars[n]
                    shapes[n] = tuple(var.shape)[1:]
            print(f"spawned server on {endpoint} (warmed "
                  f"{server.engine.cache_info()['misses']} buckets)")
        else:
            endpoint = args.endpoint
            if not shapes:
                ap.error("--endpoint needs at least one --shape name=dims")

        rng = np.random.RandomState(0)
        feeds = {n: rng.rand(args.rows, *dims).astype("float32")
                 for n, dims in shapes.items()}
        print(f"benching {endpoint}: {args.clients} closed-loop clients, "
              f"{args.duration:.0f}s, {args.rows} row(s)/request")
        r = bench(endpoint, feeds, args.clients, args.duration)
        print(f"requests={r['requests']} rejected={r['rejected']} "
              f"errors={r['errors']}")
        print(f"qps={r['qps']:.1f}  p50={r['p50_ms']:.2f}ms  "
              f"p95={r['p95_ms']:.2f}ms  p99={r['p99_ms']:.2f}ms")
        with ServingClient(endpoint) as c:
            s = c.stats()
            print(f"server: batches={s['batches']} "
                  f"avg_rows={s['avg_batch_rows']:.2f} "
                  f"fill={s['batch_fill_ratio']:.2f} "
                  f"cache={s['compile_cache']}")
        return 0 if r["errors"] == 0 else 1
    finally:
        if server is not None:
            server.close()


if __name__ == "__main__":
    sys.exit(main())

"""Closed-loop load generator for the serving engine (serving-bench entry).

Drives a ``paddle_tpu.serving.ServingServer`` with N concurrent closed-loop
clients (each sends the next request the moment the previous one returns)
for a fixed duration and reports offered QPS, latency percentiles, rejects,
and the server's own ``stats`` snapshot (batch-fill ratio, compile cache,
shed/deadline/reload counters).

Two modes:

* ``--model-dir DIR`` — spawn an in-process server over the exported dir
  (same format ``io.save_inference_model`` writes), bench it, shut down.
* ``--endpoint HOST:PORT`` — bench an already-running server; feed shapes
  then come from ``--shape name=d1,d2`` (repeatable).

``--chaos`` arms a seeded fault profile (slow device calls, injected step
faults, connection drops, queue stalls — serving/chaos.py) inside the
in-process server for the first ``--chaos-window`` seconds of the run;
clients retry with exponential backoff (``--retries``), so the report
shows the resilience layer absorbing the faults: retry counts, sheds,
deadline misses, and the server's health state returning to ``healthy``.

``--generate`` switches the clients to closed-loop autoregressive
generation against a decode-enabled server (``serving/decode.py``
continuous batching): each client submits a random prompt with a random
token budget, waits for the full stream, and repeats. The report adds the
decode plane: aggregate generated tokens/s, time-to-first-token and
inter-token latency p50/p95, mean/max KV-slot occupancy (sampled), and
the decode compile cache (steady state must show zero recompiles).

``--prefix-mix K:TLEN`` (with ``--generate``) switches the prompt shape
to the shared-prefix workload the paged KV pool exists for: K templates
of TLEN tokens each, template popularity zipf-distributed
(``--zipf-a``), each request = template + random suffix
(``--prompt-tokens`` sizes the suffix). The in-process server arms the
paged engine + radix prefix cache (docs §22; tune with
``--kv-page-len`` / ``--kv-pool-pages`` / ``--kv-overcommit`` /
``--kv-watermark``), and the report adds the prefix plane: hit rate,
hit tokens, pages in use by state, and TTFT split cold-vs-warm (first
request of a template vs the rest).

``--slo p95_ms=...,err_rate=...`` judges the finished run against
declared SLOs (obs/slo.py judge_bench) with NONZERO exit on breach — the
serving twin of bench.py's per-class bars; ``--log-json`` routes the
structured event log (obs/events.py) through stdlib logging as one-line
JSON.

Examples::

    JAX_PLATFORMS=cpu python tools/serve_bench.py --model-dir /tmp/model \
        --clients 8 --duration 10 --rows 1 --max-batch-size 16 \
        --slo p95_ms=50,err_rate=0.01
    python tools/serve_bench.py --endpoint 127.0.0.1:9000 --shape x=4
    JAX_PLATFORMS=cpu python tools/serve_bench.py --model-dir /tmp/model \
        --chaos --chaos-seed 7 --duration 6 --deadline-ms 500
    JAX_PLATFORMS=cpu python tools/serve_bench.py --model-dir /tmp/lm \
        --generate --clients 16 --duration 15 --max-slots 8 \
        --gen-tokens 8:64 --prompt-tokens 2:16
"""
from __future__ import annotations

import argparse
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from paddle_tpu.serving import (DeadlineExceeded, FleetChaos,  # noqa: E402
                                FleetOverloaded, LocalFleet, NoHealthyReplicas,
                                RetryBudgetExceeded, ServingClient,
                                ServingRejected, ServingServer,
                                TenantQuotaExceeded)
from paddle_tpu.serving.chaos import default_profile  # noqa: E402
from paddle_tpu.serving.stats import (DECODE_STAGES,  # noqa: E402
                                      PREDICT_STAGES, _percentile)


def _client_loop(endpoint, feeds, stop, out, retries, deadline_ms, seed):
    lat, done, rejected, deadline_missed, exhausted, errors = [], 0, 0, 0, 0, 0
    with ServingClient(endpoint, retries=retries, backoff_base_ms=5.0,
                       retry_seed=seed) as c:
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                c.predict(feeds, timeout_ms=deadline_ms)
                lat.append(time.monotonic() - t0)
                done += 1
            except ServingRejected:
                rejected += 1  # retries=0 path: raw structured rejection
                time.sleep(0.001)  # back off a tick before retrying
            except DeadlineExceeded:
                deadline_missed += 1  # typed terminal: the budget ran out
            except RetryBudgetExceeded:
                exhausted += 1  # typed terminal: kept rejecting/failing
            except Exception:
                errors += 1
                break
        retries_used = c.retries_total
    out.append((lat, done, rejected, deadline_missed, exhausted, errors,
                retries_used))


def _parse_range(spec, name):
    lo, _, hi = spec.partition(":")
    lo, hi = int(lo), int(hi or lo)
    if not 1 <= lo <= hi:
        raise SystemExit(f"--{name} wants LO:HI with 1 <= LO <= HI, "
                         f"got {spec!r}")
    return lo, hi


def _parse_sample(spec):
    """``T[:TOPK[:TOPP[:SEED]]]`` -> (temperature, top_k, top_p, seed)."""
    parts = spec.split(":")
    if not 1 <= len(parts) <= 4:
        raise SystemExit(f"--sample wants T[:TOPK[:TOPP[:SEED]]], "
                         f"got {spec!r}")
    try:
        temp = float(parts[0])
        top_k = int(parts[1]) if len(parts) > 1 else 0
        top_p = float(parts[2]) if len(parts) > 2 else 1.0
        seed = int(parts[3]) if len(parts) > 3 else 0
    except ValueError:
        raise SystemExit(f"--sample wants numbers in T[:TOPK[:TOPP"
                         f"[:SEED]]], got {spec!r}")
    if temp < 0 or top_k < 0 or not 0 < top_p <= 1:
        raise SystemExit(f"--sample policy out of range: {spec!r}")
    return temp, top_k, top_p, seed


def _parse_spec_knob(spec, default_draft):
    """``k=K[,draft=DIR]`` -> (k, draft_dir). Without ``draft=`` the
    target export drafts for itself (self-speculation: useful for
    plumbing/latency tests; acceptance is near 1.0 on greedy)."""
    k, draft = None, default_draft
    for part in spec.split(","):
        key, _, val = part.partition("=")
        if key == "k" and val:
            try:
                k = int(val)
            except ValueError:
                raise SystemExit(f"--spec k wants an int, got {val!r}")
        elif key == "draft" and val:
            draft = val
        else:
            raise SystemExit(f"--spec wants k=K[,draft=DIR], got {spec!r}")
    if k is None or k < 1:
        raise SystemExit(f"--spec wants k=K with K >= 1, got {spec!r}")
    return k, draft


def _gen_client_loop(endpoint, vocab, prompt_rng_seed, prompt_range,
                     token_range, stop, out, retries, deadline_ms,
                     sample=None):
    """One closed-loop generation client: random prompt + budget, wait for
    the whole stream, repeat. ``sample=(T, top_k, top_p, seed)`` turns
    every request into a sampled one (per-request seeds derived from the
    base seed so re-runs reproduce the same streams)."""
    rng = np.random.RandomState(prompt_rng_seed)
    lat, ttfts, tokens, done = [], [], 0, 0
    rejected = deadline_missed = exhausted = errors = 0
    temp, top_k, top_p, seed0 = sample or (0.0, 0, 1.0, None)
    with ServingClient(endpoint, retries=retries, backoff_base_ms=5.0,
                       retry_seed=prompt_rng_seed) as c:
        reqno = 0
        while not stop.is_set():
            prompt = rng.randint(0, vocab, size=(
                int(rng.randint(prompt_range[0], prompt_range[1] + 1)),))
            budget = int(rng.randint(token_range[0], token_range[1] + 1))
            seed = (None if seed0 is None
                    else seed0 + prompt_rng_seed * 1000003 + reqno)
            reqno += 1
            t0 = time.monotonic()
            try:
                r = c.generate(prompt, max_new_tokens=budget,
                               timeout_ms=deadline_ms,
                               temperature=temp, top_k=top_k, top_p=top_p,
                               seed=seed)
                lat.append(time.monotonic() - t0)
                ttfts.append(r["ttft_ms"] / 1e3)
                tokens += len(r["tokens"])
                done += 1
            except ServingRejected:
                rejected += 1
                time.sleep(0.001)
            except DeadlineExceeded:
                deadline_missed += 1
            except RetryBudgetExceeded:
                exhausted += 1
            except Exception:
                errors += 1
                break
        retries_used = c.retries_total
    out.append({"lat": lat, "ttft": ttfts, "tokens": tokens, "done": done,
                "rejected": rejected, "deadline_missed": deadline_missed,
                "exhausted": exhausted, "errors": errors,
                "retries": retries_used})


def bench_generate(endpoint, vocab, clients, duration, prompt_range,
                   token_range, retries=0, deadline_ms=None,
                   occupancy_poll_s=0.05, sample=None):
    """Closed-loop generation bench + an occupancy sampler riding healthz
    (the decode gauge is instantaneous; the mean NEEDS sampling)."""
    stop = threading.Event()
    out = []
    threads = [threading.Thread(target=_gen_client_loop,
                                args=(endpoint, vocab, i, prompt_range,
                                      token_range, stop, out, retries,
                                      deadline_ms, sample), daemon=True)
               for i in range(clients)]
    occ_samples = []

    def sampler():
        with ServingClient(endpoint) as c:
            while not stop.is_set():
                try:
                    d = c.healthz().get("decode")
                    if d:
                        occ_samples.append(
                            d["active_slots"] / max(d["max_slots"], 1))
                except Exception:
                    pass
                time.sleep(occupancy_poll_s)

    sampler_t = threading.Thread(target=sampler, daemon=True)
    t0 = time.monotonic()
    for t in threads:
        t.start()
    sampler_t.start()
    time.sleep(duration)
    stop.set()
    for t in threads:
        t.join(60)
    sampler_t.join(10)
    elapsed = time.monotonic() - t0
    lats = sorted(l for r in out for l in r["lat"])
    ttfts = sorted(t for r in out for t in r["ttft"])
    tokens = sum(r["tokens"] for r in out)
    done = sum(r["done"] for r in out)
    return {"elapsed_s": elapsed, "generations": done, "tokens": tokens,
            "tokens_per_s": tokens / elapsed if elapsed else 0.0,
            "rejected": sum(r["rejected"] for r in out),
            "deadline_missed": sum(r["deadline_missed"] for r in out),
            "retry_exhausted": sum(r["exhausted"] for r in out),
            "errors": sum(r["errors"] for r in out),
            "client_retries": sum(r["retries"] for r in out),
            "gen_p50_ms": _percentile(lats, 0.50) * 1e3,
            "gen_p95_ms": _percentile(lats, 0.95) * 1e3,
            "ttft_p50_ms": _percentile(ttfts, 0.50) * 1e3,
            "ttft_p95_ms": _percentile(ttfts, 0.95) * 1e3,
            "occupancy_mean": (sum(occ_samples) / len(occ_samples))
            if occ_samples else 0.0,
            "occupancy_max": max(occ_samples) if occ_samples else 0.0}


def _prefix_client_loop(endpoint, templates, zipf_p, vocab, seed,
                        suffix_range, token_range, stop, out, retries,
                        deadline_ms, seen, seen_lock):
    """One closed-loop prefix-mix client: zipf-sampled template + random
    suffix. TTFTs are split cold/warm by whether this request was the
    FIRST to issue its template fleet-wide (approximate under
    concurrency — two racing firsts both run cold but only one is
    counted cold; the split is a report, not a gate)."""
    rng = np.random.RandomState(seed)
    lat, cold_ttft, warm_ttft, tokens, done = [], [], [], 0, 0
    rejected = deadline_missed = exhausted = errors = 0
    with ServingClient(endpoint, retries=retries, backoff_base_ms=5.0,
                       retry_seed=seed) as c:
        while not stop.is_set():
            t = int(rng.choice(len(templates), p=zipf_p))
            suffix = rng.randint(0, vocab, size=(
                int(rng.randint(suffix_range[0], suffix_range[1] + 1)),))
            prompt = np.concatenate([templates[t], suffix])
            budget = int(rng.randint(token_range[0], token_range[1] + 1))
            with seen_lock:
                cold = t not in seen
                seen.add(t)
            t0 = time.monotonic()
            try:
                r = c.generate(prompt, max_new_tokens=budget,
                               timeout_ms=deadline_ms)
                lat.append(time.monotonic() - t0)
                (cold_ttft if cold else warm_ttft).append(
                    r["ttft_ms"] / 1e3)
                tokens += len(r["tokens"])
                done += 1
            except ServingRejected:
                rejected += 1
                time.sleep(0.001)
            except DeadlineExceeded:
                deadline_missed += 1
            except RetryBudgetExceeded:
                exhausted += 1
            except Exception:
                import traceback

                print(f"prefix-mix client {seed} error:\n"
                      f"{traceback.format_exc()}", file=sys.stderr)
                errors += 1
                break
        retries_used = c.retries_total
    out.append({"lat": lat, "cold_ttft": cold_ttft, "warm_ttft": warm_ttft,
                "tokens": tokens, "done": done, "rejected": rejected,
                "deadline_missed": deadline_missed, "exhausted": exhausted,
                "errors": errors, "retries": retries_used})


def bench_prefix_mix(endpoint, vocab, clients, duration, templates,
                     zipf_a, suffix_range, token_range, retries=0,
                     deadline_ms=None):
    """Closed-loop prefix-mix bench: K shared templates, zipf popularity.
    The server-side prefix/page gauges are scraped at the end — they are
    the ground truth the client-side cold/warm split approximates."""
    ranks = np.arange(1, len(templates) + 1, dtype=np.float64)
    zipf_p = ranks ** -zipf_a
    zipf_p /= zipf_p.sum()
    stop = threading.Event()
    out = []
    seen, seen_lock = set(), threading.Lock()
    threads = [threading.Thread(
        target=_prefix_client_loop,
        args=(endpoint, templates, zipf_p, vocab, i, suffix_range,
              token_range, stop, out, retries, deadline_ms, seen,
              seen_lock), daemon=True)
        for i in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(duration)
    stop.set()
    for t in threads:
        t.join(60)
    elapsed = time.monotonic() - t0
    lats = sorted(x for r in out for x in r["lat"])
    cold = sorted(x for r in out for x in r["cold_ttft"])
    warm = sorted(x for r in out for x in r["warm_ttft"])
    tokens = sum(r["tokens"] for r in out)
    done = sum(r["done"] for r in out)
    res = {"elapsed_s": elapsed, "generations": done, "tokens": tokens,
           "tokens_per_s": tokens / elapsed if elapsed else 0.0,
           "rejected": sum(r["rejected"] for r in out),
           "deadline_missed": sum(r["deadline_missed"] for r in out),
           "retry_exhausted": sum(r["exhausted"] for r in out),
           "errors": sum(r["errors"] for r in out),
           "client_retries": sum(r["retries"] for r in out),
           # whole-generation latency under the SAME keys bench_generate
           # emits, so --slo p95_ms/... judges this workload too
           "gen_p50_ms": _percentile(lats, 0.50) * 1e3,
           "gen_p95_ms": _percentile(lats, 0.95) * 1e3,
           "ttft_p50_ms": _percentile(sorted(cold + warm), 0.50) * 1e3,
           "ttft_p95_ms": _percentile(sorted(cold + warm), 0.95) * 1e3,
           "cold_generations": len(cold), "warm_generations": len(warm),
           "ttft_cold_p50_ms": _percentile(cold, 0.50) * 1e3,
           "ttft_cold_p95_ms": _percentile(cold, 0.95) * 1e3,
           "ttft_warm_p50_ms": _percentile(warm, 0.50) * 1e3,
           "ttft_warm_p95_ms": _percentile(warm, 0.95) * 1e3}
    try:
        with ServingClient(endpoint) as c:
            d = c.healthz().get("decode") or {}
            res["kv_pages"] = d.get("kv_pages") or {}
            res["prefix"] = d.get("prefix") or {}
    except Exception:
        res["kv_pages"], res["prefix"] = {}, {}
    return res


def _fleet_client_loop(router, feeds, tenant, stop, out, deadline_ms,
                       gen_spec=None):
    """One closed-loop client driving the router directly (predict, or
    generation when ``gen_spec=(vocab, prompt_range, token_range, rng)``)."""
    lat, done, tokens = [], 0, 0
    shed = quota = rejected = deadline_missed = exhausted = errors = 0
    while not stop.is_set():
        t0 = time.monotonic()
        try:
            if gen_spec is None:
                router.predict(feeds, tenant=tenant, timeout_ms=deadline_ms)
            else:
                vocab, pr, tr, rng, sample = gen_spec
                prompt = rng.randint(0, vocab, size=(
                    int(rng.randint(pr[0], pr[1] + 1)),))
                budget = int(rng.randint(tr[0], tr[1] + 1))
                temp, top_k, top_p, seed0 = sample or (0.0, 0, 1.0, None)
                r = router.generate(prompt, max_new_tokens=budget,
                                    tenant=tenant, timeout_ms=deadline_ms,
                                    temperature=temp, top_k=top_k,
                                    top_p=top_p,
                                    seed=(None if seed0 is None
                                          else seed0 + done))
                tokens += len(r["tokens"])
            lat.append(time.monotonic() - t0)
            done += 1
        except TenantQuotaExceeded as e:
            quota += 1
            time.sleep(min(e.retry_after_s, 0.05))
        except FleetOverloaded:
            shed += 1
            time.sleep(0.002)
        except (ServingRejected, NoHealthyReplicas):
            rejected += 1
            time.sleep(0.002)
        except DeadlineExceeded:
            deadline_missed += 1
        except RetryBudgetExceeded:
            exhausted += 1
        except Exception:
            errors += 1
            break
    out.append({"lat": lat, "done": done, "tokens": tokens, "shed": shed,
                "quota": quota, "rejected": rejected,
                "deadline_missed": deadline_missed, "exhausted": exhausted,
                "errors": errors, "tenant": tenant})


def bench_fleet(fleet, feeds, clients, duration, tenants=None,
                deadline_ms=None, gen_args=None):
    """Closed-loop clients (round-robin over ``tenants``) against a
    ``LocalFleet`` router; returns the aggregate + per-tenant rollup."""
    stop = threading.Event()
    out = []
    names = [t[0] for t in (tenants or [])] or [None]
    threads = []
    for i in range(clients):
        gen_spec = None
        if gen_args is not None:
            vocab, pr, tr, sample = gen_args
            gen_spec = (vocab, pr, tr, np.random.RandomState(i), sample)
        threads.append(threading.Thread(
            target=_fleet_client_loop,
            args=(fleet.router, feeds, names[i % len(names)], stop, out,
                  deadline_ms, gen_spec),
            daemon=True))
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(duration)
    stop.set()
    for t in threads:
        t.join(120)
    elapsed = time.monotonic() - t0
    lats = sorted(l for r in out for l in r["lat"])
    done = sum(r["done"] for r in out)
    return {"elapsed_s": elapsed, "requests": done,
            "tokens": sum(r["tokens"] for r in out),
            "qps": done / elapsed if elapsed else 0.0,
            "p50_ms": _percentile(lats, 0.50) * 1e3,
            "p95_ms": _percentile(lats, 0.95) * 1e3,
            "p99_ms": _percentile(lats, 0.99) * 1e3,
            "shed": sum(r["shed"] for r in out),
            "quota": sum(r["quota"] for r in out),
            "rejected": sum(r["rejected"] for r in out),
            "deadline_missed": sum(r["deadline_missed"] for r in out),
            "retry_exhausted": sum(r["exhausted"] for r in out),
            "errors": sum(r["errors"] for r in out)}


def _print_fleet_report(fleet, r):
    router = fleet.router
    print(f"requests={r['requests']} shed={r['shed']} quota={r['quota']} "
          f"rejected={r['rejected']} deadline_missed={r['deadline_missed']} "
          f"retry_exhausted={r['retry_exhausted']} errors={r['errors']}")
    if r.get("tokens"):
        print(f"tokens={r['tokens']} "
              f"tokens/s={r['tokens'] / r['elapsed_s']:.1f}")
    print(f"aggregate qps={r['qps']:.1f}  p50={r['p50_ms']:.2f}ms  "
          f"p95={r['p95_ms']:.2f}ms  p99={r['p99_ms']:.2f}ms")
    snap = router.snapshot()
    print(f"router: state={snap['fleet_state']} "
          f"pressure={snap['pressure']:.2f} "
          f"hedges={snap['hedges']} hedge_wins={snap['hedge_wins']} "
          f"failovers={snap['failovers']} "
          f"circuit_opens={snap['circuit_opens']}")
    if snap["shed_by_tenant"] or snap["quota_by_tenant"]:
        print(f"shed_by_tenant={snap['shed_by_tenant']} "
              f"quota_by_tenant={snap['quota_by_tenant']}")
    print(f"{'replica':<22}{'health':<10}{'circuit':<10}{'queue':>6}"
          f"{'occ':>5}{'served':>8}{'p95_ms':>9}{'mfu':>10}{'shards':>7}")
    for info in snap["replicas"]:
        ep = info["endpoint"]
        srv = next((s for s in fleet.servers
                    if s is not None and not getattr(s, "_closed", True)
                    and s.endpoint == ep), None)
        served, p95 = "-", "-"
        if srv is not None:
            ssnap = srv.stats.snapshot()
            served = ssnap["completed"]
            p95 = f"{ssnap['latency_ms']['p95']:.2f}"
        print(f"{ep:<22}{info['health'] or '?':<10}"
              f"{info['circuit']:<10}"
              f"{int(info['queue_depth'] or 0):>6}"
              f"{int(info['occupancy'] or 0):>5}"
              f"{served:>8}{p95:>9}"
              f"{(info['mfu'] or 0.0):>10.2e}"
              f"{info.get('shards', 1):>7}")


def bench(endpoint, feeds, clients, duration, retries=0, deadline_ms=None):
    stop = threading.Event()
    out = []
    # distinct per-client seeds: identical streams would back off in
    # lock-step — a synchronized herd is exactly what the jitter prevents
    threads = [threading.Thread(target=_client_loop,
                                args=(endpoint, feeds, stop, out, retries,
                                      deadline_ms, i), daemon=True)
               for i in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(duration)
    stop.set()
    for t in threads:
        t.join(30)
    elapsed = time.monotonic() - t0
    lats = sorted(l for ls, *_ in out for l in ls)
    done = sum(r[1] for r in out)
    return {"elapsed_s": elapsed, "requests": done,
            "rejected": sum(r[2] for r in out),
            "deadline_missed": sum(r[3] for r in out),
            "retry_exhausted": sum(r[4] for r in out),
            "errors": sum(r[5] for r in out),
            "client_retries": sum(r[6] for r in out),
            "qps": done / elapsed if elapsed else 0.0,
            "p50_ms": _percentile(lats, 0.50) * 1e3,
            "p95_ms": _percentile(lats, 0.95) * 1e3,
            "p99_ms": _percentile(lats, 0.99) * 1e3}


def _judge_slo(args, result, rc):
    """The --slo satellite: judge the finished run against declared SLOs
    (the serving twin of bench.py's per-class bars). Returns the exit
    code — nonzero on any breach."""
    if not args.slo:
        return rc
    from paddle_tpu.obs.slo import judge_bench, parse_slo_spec

    ok, lines = judge_bench(result, parse_slo_spec(args.slo))
    for line in lines:
        print(line)
    if not ok:
        print("SLO JUDGMENT: BREACH (nonzero exit)", file=sys.stderr)
        return rc or 1
    print("SLO JUDGMENT: ok")
    return rc


def _parse_tenants(specs):
    """name:priority[:rate[:burst]] -> [(name, priority, rate, burst)]."""
    out = []
    for spec in specs:
        parts = spec.split(":")
        if not 2 <= len(parts) <= 4:
            raise SystemExit(f"--tenant wants name:priority[:rate[:burst]], "
                             f"got {spec!r}")
        name = parts[0]
        prio = int(parts[1])
        rate = float(parts[2]) if len(parts) > 2 else None
        burst = float(parts[3]) if len(parts) > 3 else None
        out.append((name, prio, rate, burst))
    return out


def _main_fleet(args, shapes, tracer, quantize=None):
    """The --fleet path: N local replicas behind a FleetRouter, traffic
    driven THROUGH the router; --chaos runs the fleet-level storm.
    ``--retries`` becomes the router's per-attempt client budget
    (composed under the shared ``--fleet-retries`` failover budget);
    unlike single-server mode it defaults to 0 even under --chaos —
    the router's failover, not the inner client, owns chaos retries.
    Returns ``(exit_code, result_dict)`` so the --quantize A/B driver can
    compare lanes."""
    tenants = _parse_tenants(args.tenant)
    server_kwargs = {"max_batch_size": args.max_batch_size,
                     "batch_timeout_ms": args.batch_timeout_ms,
                     "queue_capacity": args.queue_capacity,
                     "pipeline_depth": args.pipeline_depth,
                     "quantize": quantize}
    if args.mesh is not None:
        # each replica becomes a sharded model group: the router's scraped
        # gauges (MFU, shard HBM, occupancy) aggregate across its shards
        server_kwargs["mesh"] = args.mesh
    if args.generate:
        decode = {"gen_queue_capacity": args.queue_capacity}
        if args.max_slots is not None:
            decode["max_slots"] = args.max_slots
        if args.prefill_chunk is not None:
            decode["prefill_chunk"] = args.prefill_chunk
        if args.paged_kv:
            decode["paged"] = True
        if args.spec:
            k, draft = _parse_spec_knob(args.spec, args.model_dir)
            decode["spec_draft"] = draft
            decode["spec_k"] = k
        server_kwargs["decode"] = decode
    router_kwargs = {"retries": args.fleet_retries,
                     "attempt_retries": (args.retries
                                         if args.retries is not None else 0),
                     "scrape_interval_s": 0.1,
                     "hedge_after_ms": args.hedge_ms}
    fleet = LocalFleet(args.model_dir, args.fleet,
                       server_kwargs=server_kwargs,
                       router_kwargs=router_kwargs, warmup=True)
    storm = None
    try:
        for name, prio, rate, burst in tenants:
            fleet.router.configure_tenant(name, rate=rate, burst=burst,
                                          priority=prio)
        feeds = {}
        gen_args = None
        if args.generate:
            vocab = fleet.servers[0].decode_engine.cfg["vocab"]
            pr = _parse_range(args.prompt_tokens, "prompt-tokens")
            tr = _parse_range(args.gen_tokens, "gen-tokens")
            sample = _parse_sample(args.sample) if args.sample else None
            gen_args = (vocab, pr, tr, sample)
        else:
            for n in fleet.servers[0].engine.feed_names:
                if n not in shapes:
                    var = fleet.servers[0].engine._feed_vars[n]
                    shapes[n] = tuple(var.shape)[1:]
            rng = np.random.RandomState(0)
            feeds = {n: rng.rand(args.rows, *dims).astype("float32")
                     for n, dims in shapes.items()}
        print(f"fleet of {args.fleet} replicas behind the router: "
              f"{', '.join(fleet.endpoints())}")
        if tenants:
            print("tenants: " + ", ".join(
                f"{n}(prio={p}, rate={r if r is not None else 'unlimited'})"
                for n, p, r, _ in tenants))
        if args.chaos:
            window = (args.chaos_window if args.chaos_window is not None
                      else args.duration / 2)
            storm = FleetChaos(fleet, seed=args.chaos_seed, tick_s=0.05,
                               kill_prob=0.10, restart_delay_s=0.5,
                               partition_prob=0.10, partition_s=0.4,
                               slow_prob=0.10, slow_s=0.4, slow_ms=30.0,
                               fault_window_s=window, min_alive=1)
            storm.start()
            print(f"fleet chaos armed: seed={args.chaos_seed} "
                  f"window={window:.1f}s "
                  f"(kill/restart + partition + slow-replica)")
        mode = "GENERATION" if args.generate else "predict"
        print(f"benching the router: {args.clients} closed-loop {mode} "
              f"clients, {args.duration:.0f}s")
        r = bench_fleet(fleet, feeds, args.clients, args.duration,
                        tenants=tenants, deadline_ms=args.deadline_ms,
                        gen_args=gen_args)
        if storm is not None:
            storm.stop()  # run pending heals before the report
            print(f"chaos: {storm.snapshot()}")
        _print_fleet_report(fleet, r)
        if tracer is not None:
            n = tracer.dump(args.trace_out)
            print(f"chrome trace: {args.trace_out} ({n} spans)")
        return _judge_slo(args, r, 0 if r["errors"] == 0 else 1), r
    finally:
        if storm is not None:
            storm.stop()
        fleet.close()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model-dir", help="spawn an in-process server over DIR")
    ap.add_argument("--endpoint", help="bench an already-running HOST:PORT")
    ap.add_argument("--shape", action="append", default=[],
                    metavar="name=d1,d2",
                    help="per-request trailing shape of a feed (repeatable; "
                         "required with --endpoint, optional override with "
                         "--model-dir)")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--rows", type=int, default=1,
                    help="rows per request (client-side batch)")
    ap.add_argument("--max-batch-size", type=int, default=16)
    ap.add_argument("--batch-timeout-ms", type=float, default=2.0)
    ap.add_argument("--queue-capacity", type=int, default=256)
    ap.add_argument("--pipeline-depth", type=int, default=2,
                    help="dispatch pipeline depth (1 = synchronous dispatch, "
                         "2 = overlap host prep with the in-flight device "
                         "call)")
    ap.add_argument("--retries", type=int, default=None,
                    help="client retry budget (default: 0, or 8 with "
                         "--chaos); with --fleet: the router's per-attempt "
                         "client budget, default 0 (failover owns retries)")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="per-request deadline budget; expired requests are "
                         "shed server-side before dispatch")
    ap.add_argument("--fleet", type=int, default=None, metavar="N",
                    help="spawn N local replicas behind a FleetRouter and "
                         "bench THROUGH the router (requires --model-dir); "
                         "composes with --chaos (fleet-level kill/restart/"
                         "partition/slow storm) and --generate")
    ap.add_argument("--mesh", type=int, default=None, metavar="N",
                    help="span ONE model over an N-device mesh per server "
                         "(tensor-parallel; serving/sharded.py). Composes "
                         "with --fleet: each replica is a sharded model "
                         "group whose scraped gauges (MFU, shard HBM) "
                         "aggregate across its shards. Host runs need "
                         "XLA_FLAGS=--xla_force_host_platform_device_"
                         "count set (this flag sets it when unset)")
    ap.add_argument("--tenant", action="append", default=[],
                    metavar="name:priority[:rate[:burst]]",
                    help="fleet tenant spec (repeatable); clients round-"
                         "robin over tenants. rate = token-bucket req/s "
                         "(omit for unlimited), priority = shed order "
                         "(higher survives longer)")
    ap.add_argument("--hedge-ms", type=float, default=None,
                    help="fleet hedging delay: race a second replica when "
                         "the primary hasn't answered after this many ms "
                         "(default: off)")
    ap.add_argument("--fleet-retries", type=int, default=4,
                    help="router-side shared failover budget (--fleet)")
    ap.add_argument("--chaos", action="store_true",
                    help="arm the seeded fault profile in the in-process "
                         "server (requires --model-dir); with --fleet this "
                         "is the FLEET storm: replica kills/restarts, "
                         "partitions, slow replicas")
    ap.add_argument("--chaos-seed", type=int, default=0)
    ap.add_argument("--chaos-window", type=float, default=None,
                    help="stop injecting after this many seconds (default: "
                         "half the bench duration)")
    ap.add_argument("--generate", action="store_true",
                    help="closed-loop autoregressive generation against a "
                         "decode-enabled server (continuous batching) "
                         "instead of one-shot predict")
    ap.add_argument("--gen-tokens", default="8:64", metavar="LO:HI",
                    help="per-generation max_new_tokens range (--generate)")
    ap.add_argument("--sample", metavar="T[:TOPK[:TOPP[:SEED]]]",
                    default=None,
                    help="sampled generation (--generate/--fleet loops): "
                         "temperature T with optional top-k/top-p policy "
                         "and per-request seeds derived from SEED "
                         "(default 0; streams reproduce across re-runs). "
                         "T=0 is the greedy bit-path")
    ap.add_argument("--spec", metavar="k=K[,draft=DIR]", default=None,
                    help="speculative decoding (docs §25): a draft engine "
                         "over DIR (default: the target export drafting "
                         "for itself) proposes K tokens/lane per round, "
                         "verified in one batched target step with exact "
                         "rejection sampling. Needs --model-dir + "
                         "--generate; composes with --sample, --fleet, "
                         "--mesh, and --paged-kv. Single-server runs "
                         "bench vanilla first and print the spec-vs-"
                         "vanilla tokens/s ratio")
    ap.add_argument("--prompt-tokens", default="2:16", metavar="LO:HI",
                    help="per-generation prompt length range (--generate); "
                         "with --prefix-mix this sizes the per-request "
                         "SUFFIX after the shared template")
    ap.add_argument("--prefix-mix", metavar="K:TLEN", default=None,
                    help="shared-prefix generation workload: K templates "
                         "of TLEN tokens, zipf-popular, each request = "
                         "template + random suffix. Implies --generate "
                         "and (with --model-dir) a paged-KV decode "
                         "engine; reports prefix-hit rate, pages in use, "
                         "and TTFT cold-vs-warm")
    ap.add_argument("--zipf-a", type=float, default=1.1,
                    help="zipf exponent of template popularity "
                         "(--prefix-mix)")
    ap.add_argument("--paged-kv", action="store_true",
                    help="serve decode through the paged KV pool + radix "
                         "prefix cache (docs §22) even without "
                         "--prefix-mix")
    ap.add_argument("--kv-page-len", type=int, default=None,
                    help="tokens per KV page (paged engine; default 16)")
    ap.add_argument("--kv-pool-pages", type=int, default=None,
                    help="explicit page-pool size (default: "
                         "max_slots*max_len/page_len/overcommit)")
    ap.add_argument("--kv-overcommit", type=float, default=None,
                    help="dense-positions / pool-positions ratio sizing "
                         "the default pool (default 2.0)")
    ap.add_argument("--kv-watermark", type=float, default=None,
                    help="free-page fraction below which cached prefixes "
                         "evict LRU (default 0: evict on demand only)")
    ap.add_argument("--max-slots", type=int, default=None,
                    help="KV slot pool size of the in-process decode "
                         "engine (--generate + --model-dir; default: the "
                         "decode_max_slots flag)")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="chunked-prefill size (0 = whole-prompt buckets)")
    ap.add_argument("--vocab", type=int, default=None,
                    help="prompt token id range (--generate + --endpoint; "
                         "--model-dir reads it from the export)")
    ap.add_argument("--quantize", choices=("int8", "bf16"), default=None,
                    help="A/B the weight-only quantized serving lane "
                         "(serving/quant.py) against f32 on one export: "
                         "the same bench runs twice (lane A f32, lane B "
                         "quantized), then the calibrated max-abs logit "
                         "error + greedy-token-agreement line and the "
                         "QPS/p95 (or tokens/s with --generate) ratios. "
                         "Composes with --generate, --fleet, and --mesh")
    ap.add_argument("--trace-out", metavar="FILE",
                    help="enable the obs span tracer and write a Chrome "
                         "trace (chrome://tracing / ui.perfetto.dev) of "
                         "the run; inspect with tools/paddle_cli.py trace")
    ap.add_argument("--slo", metavar="k=v,...",
                    help="judge the run against declared SLOs — e.g. "
                         "p95_ms=50,err_rate=0.01,qps_min=100 (generation "
                         "runs: tokens_per_s_min, ttft_p95_ms) — with "
                         "NONZERO exit on breach (the serving twin of "
                         "bench.py's bars)")
    ap.add_argument("--log-json", action="store_true",
                    help="route structured obs events (health "
                         "transitions, sheds, faults, chaos injections) "
                         "through stdlib logging as one-line JSON")
    ap.add_argument("--goodput", action="store_true",
                    help="arm the goodput accountant (docs §23) in the "
                         "in-process server(s) and print the per-category "
                         "request-second breakdown + goodput ratio")
    ap.add_argument("--mem", action="store_true",
                    help="arm the device-memory ledger (docs §28) in the "
                         "in-process server(s) and print the per-component "
                         "HBM table + high-water line after the run")
    args = ap.parse_args(argv)
    if args.goodput:
        # must land before server construction: the server binds its
        # registry-scoped accountant off this flag
        from paddle_tpu import flags as ptflags

        ptflags.set_flag("obs_goodput", True)
    if args.mem:
        # same ordering rule: engine construction registers its weight
        # stores and pools only when the ledger is already enabled
        from paddle_tpu import flags as ptflags

        ptflags.set_flag("obs_mem", True)
    if args.prefix_mix:
        args.generate = True  # the prefix mix IS a generation workload
    if args.log_json:
        import logging

        logging.basicConfig(level=logging.INFO,
                            format="%(name)s %(message)s")
        from paddle_tpu.obs.events import enable_json_logging

        enable_json_logging()
    if args.slo:
        # validate the spec BEFORE spending the bench time on a typo
        from paddle_tpu.obs.slo import parse_slo_spec

        try:
            parse_slo_spec(args.slo)
        except ValueError as e:
            ap.error(str(e))
    if not args.model_dir and not args.endpoint:
        ap.error("one of --model-dir / --endpoint is required")
    if args.chaos and not args.model_dir:
        ap.error("--chaos injects inside the in-process server; it needs "
                 "--model-dir")
    if args.fleet is not None and not args.model_dir:
        ap.error("--fleet spawns in-process replicas; it needs --model-dir")
    if args.quantize and not args.model_dir:
        ap.error("--quantize A/Bs quantized engines over one export; it "
                 "needs --model-dir")
    if args.spec:
        if not args.model_dir:
            ap.error("--spec builds an in-process draft engine; it needs "
                     "--model-dir")
        if not args.generate and not args.prefix_mix:
            ap.error("--spec is a generation workload; add --generate")
        _parse_spec_knob(args.spec, args.model_dir)  # fail on typos early
    if args.sample:
        if not args.generate and not args.prefix_mix:
            ap.error("--sample shapes generated tokens; add --generate")
        _parse_sample(args.sample)
    if args.mesh is not None:
        if not args.model_dir:
            ap.error("--mesh builds in-process sharded engines; it needs "
                     "--model-dir")
        # the virtual-device flag must land before jax initializes its
        # backends — this works because serve_bench only imports jax
        # lazily through the server construction below
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{max(8, args.mesh)}").strip()
    retries = args.retries if args.retries is not None else \
        (8 if args.chaos else 0)

    shapes = {}
    for spec in args.shape:
        name, _, dims = spec.partition("=")
        shapes[name] = tuple(int(d) for d in dims.split(",") if d)

    tracer = None
    if args.trace_out:
        from paddle_tpu import obs

        tracer = obs.enable()
        tracer.clear()

    if args.quantize:
        return _main_quantize_ab(args, shapes, tracer, retries)

    if args.fleet is not None:
        return _main_fleet(args, shapes, tracer)[0]

    if args.spec and not args.prefix_mix:
        return _main_spec_ab(args, shapes, tracer, retries)

    return _main_single(args, shapes, tracer, retries)[0]


def _main_spec_ab(args, shapes, tracer, retries):
    """The --spec ratio lane: the SAME generation bench twice over one
    export — lane A vanilla continuous batching, lane B speculative —
    then the spec-vs-vanilla tokens/s ratio (both lanes share --sample,
    --paged-kv, --mesh, slot knobs)."""
    import copy

    vanilla = copy.copy(args)
    vanilla.spec = None
    print("=== lane A: vanilla decode ===")
    rc_a, ra = _main_single(vanilla, dict(shapes), tracer, retries)
    print("=== lane B: speculative decode ===")
    rc_b, rb = _main_single(args, dict(shapes), tracer, retries)
    a = ra.get("tokens_per_s", 0.0)
    b = rb.get("tokens_per_s", 0.0)
    print(f"spec-vs-vanilla tokens/s: {b:.1f} vs {a:.1f} "
          f"(x{b / a if a else 0.0:.2f})")
    return rc_a or rc_b


def _main_quantize_ab(args, shapes, tracer, retries):
    """The --quantize satellite: the SAME bench twice over one export —
    lane A f32, lane B weight-only quantized — then the calibrated
    accuracy line (max abs logit error + greedy-token agreement,
    serving/quant.calibrate_error) and the A/B ratios. Composes with
    --generate (tokens/s lanes), --fleet (every replica quantized), and
    --mesh (sharded quantized engines)."""
    from paddle_tpu.serving.quant import calibrate_error

    lanes = {}
    # the baseline lane passes "" (explicit f32), NOT None: None would
    # fall back to the serving_quantize flag and quantize BOTH lanes
    for label, mode in (("f32", ""), (args.quantize, args.quantize)):
        print(f"=== lane {label} ===")
        if args.fleet is not None:
            rc, r = _main_fleet(args, shapes, tracer, quantize=mode)
        else:
            rc, r = _main_single(args, shapes, tracer, retries,
                                 quantize=mode)
        lanes[label] = (rc, r)
    cal = calibrate_error(args.model_dir, mode=args.quantize)
    print(f"calibrated accuracy ({args.quantize} vs f32): max abs logit "
          f"error {cal['max_abs_logit_err']:.3e}, greedy-token agreement "
          f"{cal['token_agreement']:.4f} over {cal['positions']} positions")
    a, b = lanes["f32"][1], lanes[args.quantize][1]

    def tokens_per_s(r):
        # bench_generate reports tokens_per_s directly; bench_fleet's
        # generation result carries raw tokens + elapsed instead
        if "tokens_per_s" in r:
            return r["tokens_per_s"]
        return r.get("tokens", 0) / r["elapsed_s"] if r["elapsed_s"] else 0.0

    if args.generate:
        ra, rb = tokens_per_s(a), tokens_per_s(b)
        lat_key = "ttft_p95_ms" if "ttft_p95_ms" in a else "p95_ms"
        print(f"A/B {args.quantize} vs f32: tokens/s {rb:.1f} vs {ra:.1f} "
              f"= {rb / ra if ra else 0.0:.3f}x  "
              f"{lat_key} {b[lat_key]:.1f} vs {a[lat_key]:.1f} ms")
    else:
        ra, rb = a["qps"], b["qps"]
        print(f"A/B {args.quantize} vs f32: QPS {rb:.1f} vs {ra:.1f} "
              f"= {rb / ra if ra else 0.0:.3f}x  "
              f"p95 {b['p95_ms']:.2f} vs {a['p95_ms']:.2f} ms")
    return lanes["f32"][0] or lanes[args.quantize][0]


def _print_goodput(s):
    """Print the server's goodput accounting block (stats RPC ``goodput``
    key, present when the server runs with obs_goodput / --goodput)."""
    gp = s.get("goodput")
    if not gp:
        return
    sv = gp.get("serving") or {}
    cats = sv.get("categories") or {}
    total = sum(cats.values())
    print(f"goodput: ratio={gp.get('goodput_ratio', 0.0):.3f} "
          f"closure={sv.get('closure', 0.0):.3f} "
          f"({sv.get('requests', 0)} requests, "
          f"{sv.get('closure_violations', 0)} closure violations)")
    if total > 0:
        parts = [f"{c}={v:.3f}s({v / total:.0%})"
                 for c, v in sorted(cats.items(), key=lambda kv: -kv[1])
                 if v > 0]
        print("  request-seconds by category: " + " ".join(parts))


def _print_mem():
    """Print the in-process memory ledger's per-component table +
    high-water line (armed by --mem / obs_mem, docs §28). The in-process
    server shares this process's ledger, so the table IS the server's
    HBM attribution at bench end."""
    from paddle_tpu.obs.mem import get_ledger

    led = get_ledger()
    if not led.enabled:
        return
    totals = led.totals()
    hw = led.high_water()
    dev = led.device_bytes()
    print(f"memory ledger: {dev / 2**20:.2f} MiB tracked on device, "
          f"high water {hw.get('total', 0) / 2**20:.2f} MiB"
          + (f", occupancy {led.occupancy():.1%}" if led.capacity else ""))
    for comp, nbytes in sorted(totals.items(), key=lambda kv: -kv[1]):
        share = nbytes / dev if dev else 0.0
        print(f"  {comp:<14} {nbytes / 2**20:10.2f} MiB ({share:.0%})  "
              f"high water {hw.get(comp, 0) / 2**20:.2f} MiB")
    host = led.totals(device="host")
    if host:
        parts = [f"{c}={v / 2**20:.2f}MiB" for c, v in sorted(host.items())]
        print("  host buffers: " + " ".join(parts))


def _main_single(args, shapes, tracer, retries, quantize=None):
    """One single-server bench lane; returns ``(exit_code, result)``."""
    server = None
    chaos = None
    try:
        if args.model_dir:
            if args.chaos:
                window = (args.chaos_window if args.chaos_window is not None
                          else args.duration / 2)
                chaos = default_profile(seed=args.chaos_seed,
                                        fault_window_s=window)
            decode = None
            if args.generate:
                decode = {}
                if args.max_slots is not None:
                    decode["max_slots"] = args.max_slots
                if args.prefill_chunk is not None:
                    decode["prefill_chunk"] = args.prefill_chunk
                decode["gen_queue_capacity"] = args.queue_capacity
                if args.spec:
                    k, draft = _parse_spec_knob(args.spec, args.model_dir)
                    decode["spec_draft"] = draft
                    decode["spec_k"] = k
                if args.paged_kv or args.prefix_mix:
                    decode["paged"] = True
                for knob, val in (("page_len", args.kv_page_len),
                                  ("pool_pages", args.kv_pool_pages),
                                  ("overcommit", args.kv_overcommit),
                                  ("evict_watermark", args.kv_watermark)):
                    if val is not None:
                        decode[knob] = val
            server = ServingServer(
                args.model_dir, max_batch_size=args.max_batch_size,
                batch_timeout_ms=args.batch_timeout_ms,
                queue_capacity=args.queue_capacity,
                pipeline_depth=args.pipeline_depth, warmup=True, chaos=chaos,
                decode=decode, mesh=args.mesh, quantize=quantize)
            endpoint = server.endpoint
            if server.engine.quant_mode:
                print(f"quantized engine: {server.engine.quant_mode} "
                      f"weight store, {server.engine.weights_bytes()} "
                      f"resident bytes")
            if args.mesh is not None:
                print(f"sharded engine: mesh dp={server.mesh_spec['dp']} "
                      f"tp={server.mesh_spec['tp']} "
                      f"({server.engine.expected_collectives_per_dispatch} "
                      f"all-gathers/dispatch)")
            for n in server.engine.feed_names:
                if n not in shapes:
                    var = server.engine._feed_vars[n]
                    shapes[n] = tuple(var.shape)[1:]
            print(f"spawned server on {endpoint} (warmed "
                  f"{server.engine.cache_info()['misses']} buckets)")
            if args.generate:
                args.vocab = server.decode_engine.cfg["vocab"]
                print(f"decode engine: slots={server.decode_engine.max_slots} "
                      f"kv_buckets={server.decode_engine.kv_buckets} "
                      f"warmed={server.decode_engine.cache_info()['misses']} "
                      f"signatures")
            if chaos is not None:
                chaos.arm()  # fault window starts with the traffic, not
                # with server construction (warmup compiles are not chaos)
                print(f"chaos armed: seed={args.chaos_seed} "
                      f"window={chaos.fault_window_s:.1f}s retries={retries}")
        else:
            endpoint = args.endpoint
            if args.generate:
                if args.vocab is None:
                    raise SystemExit("--generate --endpoint needs --vocab")
            elif not shapes:
                raise SystemExit("--endpoint needs at least one "
                                 "--shape name=dims")

        if args.prefix_mix:
            k, _, tlen = args.prefix_mix.partition(":")
            try:
                k, tlen = int(k), int(tlen)
            except ValueError:
                raise SystemExit(f"--prefix-mix wants K:TLEN, got "
                                 f"{args.prefix_mix!r}")
            if k < 1 or tlen < 1:
                raise SystemExit("--prefix-mix wants K >= 1, TLEN >= 1")
            pr = _parse_range(args.prompt_tokens, "prompt-tokens")
            tr = _parse_range(args.gen_tokens, "gen-tokens")
            trng = np.random.RandomState(12345)  # fixed: re-runs re-hit
            templates = [trng.randint(0, args.vocab, size=(tlen,))
                         for _ in range(k)]
            print(f"benching {endpoint}: {args.clients} closed-loop "
                  f"PREFIX-MIX clients, {args.duration:.0f}s — "
                  f"{k} templates x {tlen} tokens (zipf a={args.zipf_a}), "
                  f"suffixes {pr[0]}-{pr[1]}, budgets {tr[0]}-{tr[1]}")
            r = bench_prefix_mix(endpoint, args.vocab, args.clients,
                                 args.duration, templates, args.zipf_a,
                                 pr, tr, retries=retries,
                                 deadline_ms=args.deadline_ms)
            print(f"generations={r['generations']} tokens={r['tokens']} "
                  f"tokens/s={r['tokens_per_s']:.1f} "
                  f"rejected={r['rejected']} errors={r['errors']}")
            print(f"generation latency: p50={r['gen_p50_ms']:.1f}ms "
                  f"p95={r['gen_p95_ms']:.1f}ms")
            p = r.get("prefix") or {}
            queries = p.get("queries", 0)
            print(f"prefix cache: hit rate "
                  f"{p.get('hits', 0) / queries if queries else 0.0:.2%} "
                  f"({p.get('hits', 0)}/{queries} admissions, "
                  f"{p.get('hit_tokens', 0)} tokens served from cache, "
                  f"{p.get('nodes', 0)} cached pages, "
                  f"{p.get('evictions', 0)} evictions)")
            kv = r.get("kv_pages") or {}
            if kv:
                print(f"kv pages: {kv.get('active', 0)} active + "
                      f"{kv.get('cached', 0)} cached / "
                      f"{kv.get('total', 0)} total "
                      f"(page_len={kv.get('page_len')}, "
                      f"{kv.get('free', 0)} free)")
            print(f"ttft cold (first use of a template): "
                  f"p50={r['ttft_cold_p50_ms']:.1f}ms "
                  f"p95={r['ttft_cold_p95_ms']:.1f}ms "
                  f"(n={r['cold_generations']})")
            print(f"ttft warm: p50={r['ttft_warm_p50_ms']:.1f}ms "
                  f"p95={r['ttft_warm_p95_ms']:.1f}ms "
                  f"(n={r['warm_generations']})")
            if tracer is not None:
                n = tracer.dump(args.trace_out)
                print(f"chrome trace: {args.trace_out} ({n} spans)")
            return _judge_slo(args, r, 0 if r["errors"] == 0 else 1), r

        if args.generate:
            pr = _parse_range(args.prompt_tokens, "prompt-tokens")
            tr = _parse_range(args.gen_tokens, "gen-tokens")
            sample = _parse_sample(args.sample) if args.sample else None
            if sample:
                print(f"sampling: temperature={sample[0]} "
                      f"top_k={sample[1] or 'off'} "
                      f"top_p={sample[2] if sample[2] < 1 else 'off'} "
                      f"seed_base={sample[3]}")
            print(f"benching {endpoint}: {args.clients} closed-loop "
                  f"GENERATION clients, {args.duration:.0f}s, prompts "
                  f"{pr[0]}-{pr[1]} tokens, budgets {tr[0]}-{tr[1]} tokens")
            r = bench_generate(endpoint, args.vocab, args.clients,
                               args.duration, pr, tr, retries=retries,
                               deadline_ms=args.deadline_ms, sample=sample)
            print(f"generations={r['generations']} tokens={r['tokens']} "
                  f"rejected={r['rejected']} "
                  f"deadline_missed={r['deadline_missed']} "
                  f"retry_exhausted={r['retry_exhausted']} "
                  f"errors={r['errors']} "
                  f"client_retries={r['client_retries']}")
            print(f"tokens/s={r['tokens_per_s']:.1f}  "
                  f"gen p50={r['gen_p50_ms']:.1f}ms "
                  f"p95={r['gen_p95_ms']:.1f}ms  "
                  f"ttft p50={r['ttft_p50_ms']:.1f}ms "
                  f"p95={r['ttft_p95_ms']:.1f}ms")
            print(f"slot occupancy: mean={r['occupancy_mean']:.2f} "
                  f"max={r['occupancy_max']:.2f} (sampled)")
            with ServingClient(endpoint) as c:
                s = c.stats()
                d = s.get("decode") or {}
                itl = d.get("itl_ms") or {}
                print(f"server decode: tokens={d.get('tokens')} "
                      f"itl p50={itl.get('p50', 0.0):.3f}ms "
                      f"p95={itl.get('p95', 0.0):.3f}ms  "
                      f"cache={s.get('decode_compile_cache')}")
                stages = s.get("stages_ms") or {}
                for st in DECODE_STAGES:
                    if st in stages:
                        print(f"  {st:<12} mean={stages[st]['mean_ms']:8.3f} "
                              f"p95={stages[st]['p95_ms']:8.3f} "
                              f"n={stages[st]['count']}")
                sp = s.get("spec") or {}
                if sp.get("proposed"):
                    print(f"speculative: rounds={sp['rounds']} accepted="
                          f"{sp['accepted']}/{sp['proposed']} "
                          f"(acceptance {sp['acceptance_rate']:.2%})")
                _print_goodput(s)
                if "chaos" in s:
                    print(f"chaos: {s['chaos']}")
            _print_mem()
            if tracer is not None:
                n = tracer.dump(args.trace_out)
                print(f"chrome trace: {args.trace_out} ({n} spans)")
            return _judge_slo(args, r, 0 if r["errors"] == 0 else 1), r

        rng = np.random.RandomState(0)
        feeds = {n: rng.rand(args.rows, *dims).astype("float32")
                 for n, dims in shapes.items()}
        print(f"benching {endpoint}: {args.clients} closed-loop clients, "
              f"{args.duration:.0f}s, {args.rows} row(s)/request")
        r = bench(endpoint, feeds, args.clients, args.duration,
                  retries=retries, deadline_ms=args.deadline_ms)
        print(f"requests={r['requests']} rejected={r['rejected']} "
              f"deadline_missed={r['deadline_missed']} "
              f"retry_exhausted={r['retry_exhausted']} errors={r['errors']} "
              f"client_retries={r['client_retries']}")
        print(f"qps={r['qps']:.1f}  p50={r['p50_ms']:.2f}ms  "
              f"p95={r['p95_ms']:.2f}ms  p99={r['p99_ms']:.2f}ms")
        with ServingClient(endpoint) as c:
            s = c.stats()
            print(f"server: state={s.get('state')} batches={s['batches']} "
                  f"avg_rows={s['avg_batch_rows']:.2f} "
                  f"fill={s['batch_fill_ratio']:.2f} "
                  f"cache={s['compile_cache']}")
            print(f"server: rejected={s['rejected']} shed={s['shed']} "
                  f"deadline_exceeded={s['deadline_exceeded']} "
                  f"failed={s['failed']} reloads={s['reloads']} "
                  f"weights_version={s.get('weights_version')}")
            p = s.get("pipeline", {})
            print(f"pipeline: depth={s.get('pipeline_depth')} "
                  f"occupancy={p.get('device_queue_occupancy')} "
                  f"occupancy_max={p.get('device_queue_occupancy_max')} "
                  f"single_request_batches={s.get('single_request_batches')}")
            stages = s.get("stages_ms") or {}
            if stages:
                # the per-stage breakdown the spans buy us: where a
                # request's latency actually went (docs/design.md §15)
                print("stage breakdown (per-request ms, "
                      "mean/p95 over the retained window):")
                order = PREDICT_STAGES  # the one stage list (stats.py)
                total_mean = 0.0
                for st in order:
                    d = stages.get(st)
                    if not d:
                        continue
                    total_mean += d["mean_ms"]
                    print(f"  {st:<14} mean={d['mean_ms']:8.3f}  "
                          f"p95={d['p95_ms']:8.3f}  n={d['count']}")
                srv_mean = s.get("latency_ms", {}).get("mean", 0.0)
                print(f"  {'sum(means)':<14} {total_mean:13.3f}  "
                      f"(vs server mean latency {srv_mean:.3f}ms)")
            if s.get("flops_per_s"):
                print(f"mfu: {s.get('mfu', 0.0):.3e} "
                      f"(cost-analysis {s['flops_per_s'] / 1e9:.4f} GFLOP/s)")
            _print_goodput(s)
            if "chaos" in s:
                print(f"chaos: {s['chaos']}")
        _print_mem()
        if tracer is not None:
            n = tracer.dump(args.trace_out)
            print(f"chrome trace: {args.trace_out} ({n} spans; "
                  f"summarize with tools/paddle_cli.py trace)")
        return _judge_slo(args, r, 0 if r["errors"] == 0 else 1), r
    finally:
        if server is not None:
            server.close()


if __name__ == "__main__":
    sys.exit(main())
